"""Supervised multi-process worker pool: crash containment at process
granularity.

Everything the resilience layer built so far — breaker, watchdog, guards,
`serve` — lives in ONE process, so a native SIGSEGV, a wedged XLA tunnel
or an OOM still takes the whole service down, and `watchdog.py` can only
*abandon* a hung thread (an unbounded leak it already counts). SeGraM
(arXiv:2205.05883) and AnySeq/GPU (arXiv:2205.07610) get both their
throughput and their fault story from independent execution units; this
module gives abpoa-tpu the same property on any multicore host: alignment
jobs (one read set, or one serve request) execute in spawned worker
PROCESSES under a supervisor that can always reclaim them.

Supervision contract (the tentpole of ISSUE 13):

- **heartbeats**: a worker thread beats every ``ABPOA_TPU_POOL_HEARTBEAT_S``
  (default 1 s) while a job executes, carrying the worker's resident-set
  size; the supervisor reads them without blocking the result path.
- **hard SIGKILL on deadline expiry** (``ABPOA_TPU_POOL_DEADLINE_S``,
  default 900 s like the dispatch watchdog; serve jobs carry their own
  request budget): past the deadline the whole worker process is killed —
  thread, stack, device handle reclaimed in one stroke. This REPLACES
  thread abandonment for pool-routed work: `watchdog.supervision_needed`
  returns False inside a pool worker.
- **crash containment**: a worker SIGSEGV/OOM/kill ends one job's process;
  the supervisor records a classified fault and lives on.
- **restart with exponential backoff**: a slot whose workers keep dying
  respawns at ``ABPOA_TPU_POOL_BACKOFF_S`` (default 0.5 s) doubling to a
  30 s cap; one clean job resets the ladder.
- **RSS budget** (``ABPOA_TPU_POOL_RSS_MB``): priced by
  `resilience/memory.py` when unset — the device-byte admission budget
  (plus runtime baseline) where one is active, or the per-job footprint
  estimate the serve admission queue already computed; 0 disables. A
  worker whose heartbeat exceeds the budget is hard-killed before the
  host OOM killer picks a victim at random.
- **exactly-once requeue / poison-job quarantine**: a job whose worker
  DIED (crash, RSS kill, stall kill) is retried once on a fresh worker;
  a second death quarantines it as a poison job with a structured fault
  record (`poison_job`) — rc stays 0 while any healthy set succeeded.
  A DEADLINE kill is terminal immediately: the budget is spent, exactly
  like a watchdog `DispatchTimeout` (hangs are not retryable).

Worker model: plain subprocesses running ``python -m
abpoa_tpu.parallel.pool_worker`` speaking length-prefixed pickle frames
over stdin/stdout — NOT a multiprocessing.Pool. A spawn-context Pool
re-imports the parent's ``__main__`` in every child (breaks under
REPL/pytest entry points); a fork-context Pool would inherit a
half-initialized XLA runtime. The subprocess protocol depends on neither,
and gives the supervisor a real pid to SIGKILL.

Fault-injection brokering: count-limited ``ABPOA_TPU_INJECT`` budgets are
leased by the supervisor to one in-flight job at a time and the unfired
remainder refunded (see `resilience/inject.py`), so ``poison_set:1``
still means ONE poisoned set across the whole pool run instead of one per
worker process. The ``worker_kill``/``worker_sigsegv`` kinds fire from
the supervisor itself: the shot is consumed (and counted) in the parent,
and the doomed job's dispatch frame carries the tag; subsequent shots of
the same kind stay bound to that job's retries, which is what makes
``worker_sigsegv:2`` deterministically produce one twice-crashed —
quarantined — job.

Telemetry: `abpoa_pool_workers` (live ready workers),
`abpoa_pool_restarts_total`, `abpoa_pool_kills_total`,
`abpoa_pool_requeues_total`, `abpoa_pool_poison_jobs_total` and the
worker compile counters, all materialized at pool start so "zero kills"
is readable as 0 rather than as an absent family. Worker run-report
deltas (counters, fault records, breaker state, true-XLA-compile counts)
merge into the parent report after every job, so `--report`, `--metrics`,
`abpoa-tpu top` and the chaos assertions see one coherent story even when
the interesting events happened in a child process.

The pool needs no new compile-ladder rungs: each worker runs the same
declared K=1 signatures as the in-process drivers, against the shared
persistent XLA cache — which is also what makes a RESTARTED worker warm
(cache loads, no recompile burst).

Request tracing + flight recorder (PR 15): every job's dispatch frame
carries the request id minted at ingress and the ATTEMPT number; the
worker runs the job under that trace context (one always-open `job:`
span), ships its span delta back with the result (rebased parent-side
onto the observed dispatch time — one request tree across the pipe), and
keeps an always-on flight recorder persisted on each heartbeat. When the
supervisor kills a worker or observes a crash it harvests the dump,
enriches it with the observed cause (the worker cannot record its own
SIGKILL), and attaches it to the fault + archive records — the feed
`abpoa-tpu why` renders into a causal verdict.
"""
from __future__ import annotations

import io
import itertools
import os
import pickle
import select
import signal
import struct
import subprocess
import sys
import threading
import time
from collections import deque
from typing import IO, Dict, List, Optional, Sequence

_FRAME_HDR = struct.Struct("<Q")

# worker-process state, filled by worker_init (runs in the WORKER)
_W: dict = {}


# --------------------------------------------------------------------------- #
# knobs                                                                       #
# --------------------------------------------------------------------------- #

def job_deadline_s() -> float:
    """Per-job hard-kill deadline. Sized like the dispatch watchdog (a
    cold first-sight compile is minutes and must never trip it); serve
    jobs override with their request budget. 0 disables."""
    return float(os.environ.get("ABPOA_TPU_POOL_DEADLINE_S", "900"))


def heartbeat_s() -> float:
    return max(0.05, float(os.environ.get("ABPOA_TPU_POOL_HEARTBEAT_S",
                                          "1.0")))


def stall_s() -> float:
    """Kill a worker whose heartbeat goes silent this long mid-job. 0
    (default) disables: a native kernel holding the GIL beats late
    without being wedged, and the job deadline is the hard bound either
    way — stall detection is an opt-in early trigger."""
    return float(os.environ.get("ABPOA_TPU_POOL_STALL_S", "0"))


def backoff_base_s() -> float:
    return float(os.environ.get("ABPOA_TPU_POOL_BACKOFF_S", "0.5"))


_BACKOFF_CAP_S = 30.0


def restart_backoff_s(consec_deaths: int) -> float:
    """Exponential respawn backoff: 0 for the first spawn, then
    base * 2^(n-1) capped at 30 s for consecutive deaths."""
    if consec_deaths <= 0:
        return 0.0
    return min(_BACKOFF_CAP_S, backoff_base_s() * (2 ** (consec_deaths - 1)))


def spawn_timeout_s() -> float:
    return float(os.environ.get("ABPOA_TPU_POOL_SPAWN_TIMEOUT_S", "180"))


# worker baseline (interpreter + numpy/jax runtime + graph engine) and the
# host-side headroom over the DEVICE-byte footprint model: host copies,
# Python objects and allocator slack make resident bytes a small multiple
# of the plane estimate
_BASE_RSS_BYTES = 1_500 * 10 ** 6
_EST_HEADROOM = 6


def rss_limit_bytes(est_bytes: Optional[int] = None) -> int:
    """Per-worker RSS kill ceiling. ``ABPOA_TPU_POOL_RSS_MB`` wins (0
    disables); otherwise priced by resilience/memory.py: baseline + the
    active device admission budget when one exists, else baseline + a
    headroom multiple of this job's own footprint estimate (the serve
    admission queue computes one per request), else disabled — host RAM
    is elastic and a blind default would kill honest big sets."""
    env = os.environ.get("ABPOA_TPU_POOL_RSS_MB")
    if env is not None:
        mb = float(env)
        return int(mb * 1e6) if mb > 0 else 0
    from ..resilience import memory
    budget = memory.budget_bytes()
    if budget:
        return _BASE_RSS_BYTES + budget
    if est_bytes:
        return _BASE_RSS_BYTES + _EST_HEADROOM * int(est_bytes)
    return 0


def explicit_workers(abpt) -> int:
    """THE parser for the operator's explicit worker count: CLI
    ``--workers`` / `Params.workers` wins, then ``ABPOA_TPU_WORKERS``.
    Returns 0 when unset/auto; a typo'd env value warns once and counts
    as unset (never a traceback mid-batch). Shared by resolve_workers and
    the scheduler's hybrid opt-in so the knob has exactly one grammar."""
    w = int(getattr(abpt, "workers", 0) or 0)
    if w > 0:
        return w
    env = os.environ.get("ABPOA_TPU_WORKERS", "").strip().lower()
    if env and env != "auto":
        try:
            return max(0, int(env))
        except ValueError:
            print(f"Warning: ignoring ABPOA_TPU_WORKERS={env!r} "
                  "(expected an integer or 'auto')", file=sys.stderr)
    return 0


def resolve_workers(abpt, n_sets: int) -> int:
    """Worker-process count for a batch of `n_sets` independent sets:
    the explicit count (explicit_workers) wins; auto = one worker per
    available core (the ROUND8 finding: the K=1 engine is the fastest
    per-set configuration on CPU hosts, so multiple sets scale with
    processes, not with vmapped lockstep), never more than there are
    sets.

    Auto NEVER pools device-family backends (jax/tpu/pallas): N worker
    processes would each open their own accelerator client against the
    same (often exclusive) device, and the pool branch bypasses the
    wedged-tunnel probe the in-process path runs first. An explicit
    --workers / env count is the operator's call and passes through."""
    w = explicit_workers(abpt)
    if w > 0:
        return max(1, min(w, max(1, n_sets)))
    if n_sets <= 1 or abpt.device in ("jax", "tpu", "pallas"):
        return 1
    try:
        cpus = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cpus = os.cpu_count() or 1
    return max(1, min(cpus, n_sets))


# --------------------------------------------------------------------------- #
# frame protocol (shared with pool_worker.py)                                 #
# --------------------------------------------------------------------------- #

def write_frame(fp, obj) -> None:
    blob = pickle.dumps(obj)
    fp.write(_FRAME_HDR.pack(len(blob)) + blob)
    fp.flush()


def _read_exact(fp, n: int) -> bytes:
    chunks = []
    while n:
        b = fp.read(n)
        if not b:
            raise EOFError("pool worker closed its pipe")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def read_frame(fp):
    (n,) = _FRAME_HDR.unpack(_read_exact(fp, _FRAME_HDR.size))
    return pickle.loads(_read_exact(fp, n))


# --------------------------------------------------------------------------- #
# worker side (executed inside pool_worker.main)                              #
# --------------------------------------------------------------------------- #

def worker_init(init: dict) -> None:
    """Runs in the WORKER before the ready handshake: one obs run for the
    worker's lifetime (so the breaker carries state across jobs exactly
    like a long-lived serial process), core dumps off (injected SIGSEGVs
    are a designed failure mode, not a debuggable event), Params
    unpickled once. The span tracer is armed for the worker's lifetime
    (bounded ring — the PR-7 overhead contract) and the flight recorder
    installed on top of it: the always-on black box the supervisor
    harvests when it kills us (obs/flight.py)."""
    try:
        import resource
        resource.setrlimit(resource.RLIMIT_CORE, (0, 0))
    except (ImportError, OSError, ValueError):
        pass
    from .. import obs
    from ..obs import flight
    obs.start_run()
    obs.trace_enable()
    _W["abpt"] = pickle.loads(init["params"])
    _W["label"] = init.get("label", "pool")
    flight.install(label=_W["label"])


def worker_rss_bytes() -> int:
    """This process's resident-set size (Linux /proc; 0 = unknown, which
    disables RSS enforcement for the frame rather than killing blind)."""
    try:
        with open("/proc/self/statm") as fp:
            return int(fp.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE")
                                                or 4096)
    except (OSError, IndexError, ValueError):
        return 0


def heartbeat_loop(out, wlock: threading.Lock, job_id: int,
                   stop: threading.Event) -> None:
    """Beat (job id + RSS) while the job executes. Beats only during
    execution: an idle worker writing unread frames would eventually fill
    the pipe and wedge its own result write behind the full buffer. Each
    beat also persists the flight-recorder dump (atomic rename), so a
    kill at any instant leaves a record at most one beat stale."""
    from ..obs import flight
    hb = heartbeat_s()
    while not stop.wait(hb):
        rss = worker_rss_bytes()
        flight.beat(rss)
        try:
            with wlock:
                write_frame(out, ("hb", job_id, rss))
        except (OSError, ValueError):
            return


def _report_snapshot():
    from ..obs import metrics
    from ..obs import report
    from ..obs import compile_log as clog
    rep = report()
    # the raw record lists (reads, faults, compile records) are the
    # per-job transport to the parent; clear them each job so a
    # long-lived worker never hits READS_CAP/FAULTS_CAP/RECORDS_CAP and
    # silently stops contributing — the parent report owns the
    # cumulative view
    with metrics._MUT:
        del rep.reads[:]
        rep.reads_dropped = 0
        del rep.faults[:]
        rep.faults_dropped = 0
        del clog._RECORDS[:]
        sk = rep.wall_sketch
        # sketch buckets + backend/fallback attribution are cumulative
        # (never cleared): snapshot them so the delta carries EVERY read
        # of the job, not just the READS_CAP-bounded raw list
        reads_agg0 = (list(sk.counts), sk.count, sk.sum,
                      dict(rep.read_backends), dict(rep.read_fallbacks),
                      rep.reads_amortized)
    return (dict(rep.counters),
            {k: tuple(v) for k, v in rep.phases.items()},
            {k: tuple(v) for k, v in rep.values.items()},
            reads_agg0)


def _report_delta(snap) -> dict:
    """What one job changed in this worker's run report: counter/phase/
    value deltas, per-read records, new fault records, current breaker-
    degradation state, and the job's compile story split into true XLA
    compiles vs persistent-cache loads (the recompile-burst signal the
    serve smoke asserts on)."""
    from ..obs import metrics
    from ..obs import report
    from ..obs import compile_log as clog
    rep = report()
    c0, p0, v0, (sc0, sn0, ss0, bk0, fb0, am0) = snap
    counters = {}
    for k, v in rep.counters.items():
        d = v - c0.get(k, 0)
        if d:
            counters[k] = d
    phases = {}
    for k, (w, c) in rep.phases.items():
        w0, c0p = p0.get(k, (0.0, 0))
        if c != c0p or w != w0:
            phases[k] = [w - w0, c - c0p]
    values = {}
    for k, (n, tot, vmin, vmax) in rep.values.items():
        n0, tot0, _m0, _x0 = v0.get(k, (0, 0.0, 0.0, 0.0))
        if n != n0:
            # min/max of the job alone are unknowable from cumulative
            # state; the whole-worker extremes are a safe superset
            values[k] = [n - n0, tot - tot0, vmin, vmax]
    xla = loads = 0
    for rec in clog._RECORDS:
        if not rec.get("cache_hit"):
            # only a positively-witnessed persistent-cache hit counts as a
            # load; None (cache disabled / no monitoring events) means the
            # compile really ran from scratch — counting it as a load would
            # let the serve-smoke recompile-burst gate pass vacuously
            if rec.get("persistent_cache_hit") is True:
                loads += 1
            else:
                xla += 1
    with metrics._MUT:
        sk = rep.wall_sketch
        # aggregate view of EVERY read this job recorded — the raw list
        # below is capped at READS_CAP, and a replay of it alone would
        # silently undercount the parent's percentiles/counts past the
        # cap. Job-local min/max are unknowable from cumulative state;
        # the worker-lifetime extremes are a safe superset (same
        # convention as the values merge above)
        reads_agg = {
            "counts": {i: c - sc0[i] for i, c in enumerate(sk.counts)
                       if c != sc0[i]},
            "count": sk.count - sn0, "sum": sk.sum - ss0,
            "min": sk.min, "max": sk.max,
            "backends": {b: n - bk0.get(b, 0)
                         for b, n in rep.read_backends.items()
                         if n != bk0.get(b, 0)},
            "fallbacks": {f: n - fb0.get(f, 0)
                          for f, n in rep.read_fallbacks.items()
                          if n != fb0.get(f, 0)},
            "amortized": rep.reads_amortized - am0,
            "dropped": rep.reads_dropped,
        }
    return {"counters": counters,
            "phases": phases,
            "values": values,
            "read_records": [tuple(r) for r in rep.reads],
            "reads_agg": reads_agg,
            "faults": list(rep.faults),
            "degraded": {b: dict(i) for b, i in rep.degraded.items()},
            "xla_compiles": xla, "cache_loads": loads}


def _test_delay_s() -> float:
    """Per-job service-time shim (ABPOA_TPU_POOL_DELAY_S): makes "a job is
    in flight" a deterministic window for the drain/deadline tests, same
    spirit as ABPOA_TPU_SERVE_DELAY_S."""
    return float(os.environ.get("ABPOA_TPU_POOL_DELAY_S", "0") or 0)


def run_file(payload) -> dict:
    """One `-l` batch job: file -> output text, with the same per-set
    quarantine boundary the serial runner applies (the fault record and
    stderr line are produced HERE and merged to the parent)."""
    from .. import resilience as rz
    from ..pipeline import Abpoa, msa_from_file
    idx, fn = payload
    abpt = _W["abpt"]
    abpt.batch_index = idx + 1
    buf = io.StringIO()
    quarantined = None
    try:
        msa_from_file(Abpoa(), abpt, fn, buf)
    except rz.QUARANTINE_EXCEPTIONS as e:
        rz.quarantine_set(idx, fn, e)
        quarantined = (type(e).__name__, str(e)[:300])
    return {"idx": idx, "text": buf.getvalue(), "quarantined": quarantined}


def run_records(payload) -> dict:
    """One serve job: in-memory records -> the same bytes `_run_single`
    would produce in-process (the byte-identity contract of the smoke)."""
    from .. import resilience as rz
    from ..pipeline import Abpoa, msa
    from ..serve.server import _test_delay_s as serve_delay_s
    (records,) = payload
    delay = serve_delay_s()  # one parser for the serve-path delay shim
    if delay:
        time.sleep(delay)
    buf = io.StringIO()
    quarantined = None
    try:
        msa(Abpoa(), _W["abpt"], records, buf)
    except rz.QUARANTINE_EXCEPTIONS as e:
        from ..obs import record_fault
        record_fault("poisoned_set", detail=str(e)[:300],
                     action="rejected_400")
        quarantined = (type(e).__name__, str(e)[:300])
    return {"text": buf.getvalue(), "quarantined": quarantined}


def run_group(payload) -> dict:
    """One hybrid-route job: a split-lockstep group of `-l` files inside
    this worker (the scheduler's pool-of-lockstep-groups). Per-file texts
    come back keyed by file index so the parent emits in file order."""
    from .runner import run_lockstep_files
    pairs = payload  # [(file_idx, path), ...]
    return run_lockstep_files(pairs, _W["abpt"])


_TASKS = {"file": run_file, "records": run_records, "group": run_group}


def worker_run_job(job_id: int, kind: str, payload, spec: str,
                   kill_kind: Optional[str], meta: Optional[dict] = None):
    """Execute one job frame in the worker. `spec` is the injection lease
    the supervisor brokered for THIS job; `kill_kind` is a supervisor-
    fired worker-death injector — die first, run never. `meta` carries
    the request context: the id minted at ingress (serve request / `-l`
    set), the ATTEMPT number (so a requeued request's two attempts stay
    distinct in traces and merged records instead of conflating under one
    job), and whether the parent wants this job's span delta shipped back
    with the result."""
    from ..obs import flight, trace
    from ..resilience import inject
    meta = meta or {}
    rid = meta.get("rid") or ""
    attempt = int(meta.get("attempt") or 1)
    # the flight recorder learns the job context BEFORE any chance of
    # death: an injected kill below must still leave a dump naming us
    flight.begin_job(rid, attempt, kind, label=meta.get("label", ""))
    if kill_kind:
        sig = (signal.SIGKILL if kill_kind == "worker_kill"
               else signal.SIGSEGV)
        os.kill(os.getpid(), sig)
        time.sleep(10)  # signal delivery can lag; never answer the frame
    inject.configure(spec or "")
    snap = _report_snapshot()
    n0 = trace.tracer()._n
    t_job0 = time.perf_counter()
    status = "done"
    try:
        # the job span is the worker-side envelope: always OPEN while the
        # job executes (the flight dump's "killed mid what?" answer) and
        # the root of the worker half of the request's span tree. The
        # service-time shim sleeps inside it — it models service time.
        with trace.request_ctx(rid, attempt), \
                trace.span(f"job:{kind}", "job",
                           args={"label": meta.get("label", ""),
                                 "pid": os.getpid()}):
            delay = _test_delay_s()
            if delay:
                time.sleep(delay)
            result = _TASKS[kind](payload)
    except Exception:
        status = "error"
        raise
    finally:
        flight.end_job(status)
    ext = _report_delta(snap)
    ext["attempt"] = attempt
    if meta.get("trace"):
        # ship the job's span delta with times rebased to the job start;
        # the parent re-anchors them on ITS observed dispatch time and
        # merges them into the per-request tree (one trace across the
        # pipe boundary)
        evs, dropped = trace.tracer().events_since(n0)
        ext["spans"] = [(k, name, cat, ts - t_job0, dur, args, req)
                        for k, name, cat, ts, dur, _tid, args, req in evs]
        ext["spans_dropped"] = dropped
    result["extract"] = ext
    return "ok", job_id, result


# --------------------------------------------------------------------------- #
# parent side                                                                 #
# --------------------------------------------------------------------------- #

class PoolWorkerError(RuntimeError):
    """A worker reported an unclassified failure; the batch runner
    re-raises it (real bugs must propagate, same as serial)."""


class PoolJob:
    """One unit of pool work moving toward a terminal status:
    ok | timeout | poison | error | cancelled."""

    _ids = itertools.count(1)

    __slots__ = ("id", "kind", "payload", "label", "deadline_s",
                 "deadline_ts", "est_bytes", "attempts", "status",
                 "result", "error", "done", "t_submit", "leases",
                 "rid", "trace", "dumps")

    def __init__(self, kind: str, payload, label: str = "",
                 deadline_s: Optional[float] = None,
                 est_bytes: Optional[int] = None,
                 rid: str = "", trace: bool = False) -> None:
        self.id = next(self._ids)
        self.kind = kind
        self.payload = payload
        self.label = label or f"job-{self.id}"
        self.deadline_s = deadline_s
        # request context (PR 15): the id minted at ingress rides the
        # dispatch frame into the worker; `trace` asks the worker to ship
        # its span delta back; `dumps` collects harvested flight dumps
        # across attempts (newest last)
        self.rid = rid
        self.trace = trace
        self.dumps: List[str] = []
        # an EXPLICIT deadline is a wall budget from submission (a serve
        # request's remaining_s): it spans queue wait, every attempt and
        # respawn backoff — a requeue must not reset the clock. Jobs
        # without one get the pool default per ATTEMPT instead (batch
        # jobs queue behind each other for unbounded, healthy time).
        self.deadline_ts = (time.monotonic() + deadline_s
                            if deadline_s is not None and deadline_s > 0
                            else None)
        self.est_bytes = est_bytes
        self.attempts = 0
        self.status: Optional[str] = None
        self.result: dict = {}
        self.error = ""
        self.done = threading.Event()
        self.t_submit = time.perf_counter()
        self.leases: Dict[str, int] = {}

    def finish(self, status: str, result: Optional[dict] = None,
               error: str = "") -> None:
        if self.status is not None:
            return
        self.status = status
        if result is not None:
            self.result = result
        self.error = error
        self.done.set()

    def wall_s(self) -> float:
        return time.perf_counter() - self.t_submit


class _Slot:
    """One worker seat: at most one live process, one supervisor thread."""

    __slots__ = ("proc", "stdin", "stdout", "pid", "ready", "spawned",
                 "consec_deaths", "rss", "retired")

    def __init__(self) -> None:
        self.proc = None
        self.stdin = None
        self.stdout = None
        self.pid = 0
        self.ready = False
        self.spawned = 0
        self.consec_deaths = 0
        self.rss = 0
        self.retired = False


class WorkerPool:
    """The supervisor: N slots x (spawn, dispatch, watch, kill, respawn)."""

    def __init__(self, n_workers: int, abpt, label: str = "pool",
                 default_deadline_s: Optional[float] = None) -> None:
        self.n_workers = max(1, int(n_workers))
        self.label = label
        self._default_deadline = (default_deadline_s
                                  if default_deadline_s is not None
                                  else job_deadline_s())
        self._params_blob = pickle.dumps(abpt)
        self._queue: deque = deque()
        self._cv = threading.Condition()
        self._closing = False
        self._aborting = False
        self._draining = False
        self._slots = [_Slot() for _ in range(self.n_workers)]
        self._threads: List[threading.Thread] = []
        self._state = threading.Lock()
        self._kill_bound: Optional[int] = None
        self._slot_degraded: Dict[int, dict] = {}
        self._deg_counts: Dict[str, int] = {}
        # pool-local mirrors of the process-cumulative obs counters, for
        # /healthz and snapshot()
        self._counts = {"restarts": 0, "kills": 0, "requeues": 0,
                        "poison_jobs": 0, "crashes": 0, "jobs": 0,
                        "flight_dumps": 0}
        self._stall = stall_s()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        from ..obs import metrics
        metrics.materialize_pool_families()
        for si in range(self.n_workers):
            t = threading.Thread(target=self._supervise, args=(si,),
                                 daemon=True,
                                 name=f"abpoa-pool-{self.label}-{si}")
            t.start()
            self._threads.append(t)

    def wait_ready(self, timeout: float = 60.0) -> bool:
        """Block until every slot's worker answered the ready handshake
        (or timeout). Optional — jobs queue safely before readiness."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if sum(1 for s in self._slots if s.ready) >= self.n_workers:
                return True
            time.sleep(0.05)
        return False

    def submit(self, kind: str, payload, label: str = "",
               deadline_s: Optional[float] = None,
               est_bytes: Optional[int] = None,
               rid: str = "", trace: bool = False) -> PoolJob:
        job = PoolJob(kind, payload, label=label, deadline_s=deadline_s,
                      est_bytes=est_bytes, rid=rid, trace=trace)
        with self._cv:
            if self._closing or self._draining:
                job.finish("cancelled", error="pool is draining")
                return job
            self._queue.append(job)
            self._cv.notify()
        return job

    def drain_intake(self) -> int:
        """SIGTERM drain: cancel every QUEUED job (they never started),
        let in-flight jobs finish. Returns the number cancelled."""
        with self._cv:
            self._draining = True
            cancelled = 0
            while self._queue:
                self._queue.popleft().finish("cancelled",
                                             error="drained on signal")
                cancelled += 1
            self._cv.notify_all()
        return cancelled

    def close(self, graceful: bool = True, timeout: float = 30.0) -> None:
        """Tear the pool down. graceful: in-flight jobs finish, workers
        get a shutdown frame; else everything is SIGKILLed now and
        unfinished jobs become `cancelled`."""
        with self._cv:
            self._closing = True
            if not graceful:
                self._aborting = True
            while self._queue:
                self._queue.popleft().finish("cancelled",
                                             error="pool closed")
            self._cv.notify_all()
        if not graceful:
            for s in self._slots:
                self._kill_proc(s)
        for t in self._threads:
            t.join(timeout=timeout)
        for s in self._slots:  # belt: no worker survives close()
            self._kill_proc(s)
        self._publish_up()

    def snapshot(self) -> dict:
        with self._state:
            return {
                "target": self.n_workers,
                "workers": sum(1 for s in self._slots if s.ready),
                "pids": [s.pid for s in self._slots if s.ready],
                **dict(self._counts),
            }

    # ------------------------------------------------------------ internals
    def _publish_up(self) -> None:
        from ..obs import metrics
        if metrics.enabled():
            metrics.publish_pool_workers(
                sum(1 for s in self._slots if s.ready))

    def _bump(self, key: str, counter: Optional[str] = None,
              n: int = 1) -> None:
        with self._state:
            self._counts[key] = self._counts.get(key, 0) + n
        if counter:
            from ..obs import count
            count(counter, n)

    def _next_job(self, si: int) -> Optional[PoolJob]:
        while True:
            with self._cv:
                if self._queue:
                    return self._queue.popleft()
                if self._closing or self._draining:
                    return None
                self._cv.wait(0.25)
            # heal the slot NOW: a SIGKILLed idle worker must show up as
            # a crash + respawn in /healthz (not lie ready until the next
            # job trips over its corpse), and a slot emptied by a hard
            # kill must regain capacity before the next job, not because
            # of it
            self._heal_slot(si)

    def _heal_slot(self, si: int) -> None:
        if self._closing or self._draining:
            return
        slot = self._slots[si]
        if (slot.proc is not None and slot.ready
                and slot.proc.poll() is not None):
            self._note_death(si, None)
        if slot.proc is None or slot.proc.poll() is not None:
            # opportunistic: one spawn attempt per idle tick (backoff
            # still applies) — a permanently-broken worker command must
            # not spawn-storm from the heal loop
            self._ensure_worker(si, max_attempts=1)

    def _requeue_front(self, job: PoolJob) -> None:
        with self._cv:
            if self._closing or self._draining:
                job.finish("cancelled", error="pool is draining")
                return
            self._queue.appendleft(job)
            self._cv.notify()

    def _supervise(self, si: int) -> None:
        # eager spawn: serve wants warm workers before the first request
        # arrives (wait_ready), and a batch has its jobs queued already
        self._ensure_worker(si)
        while True:
            job = self._next_job(si)
            if job is None:
                break
            try:
                self._execute(si, job)
            except Exception as exc:  # noqa: BLE001 — supervisor must live
                # the containment layer cannot itself lose a job: an escaped
                # exception becomes the job's error (finish is idempotent),
                # never an unset done event that wedges its waiter
                from ..obs import record_fault
                record_fault("supervisor_error", detail=f"{job.label}: "
                             f"{type(exc).__name__}: {exc}"[:300],
                             action="propagated")
                self._refund_leases(job, fired=None)
                self._unbind_kill(job)
                self._kill_proc(self._slots[si])
                job.finish("error",
                           error=f"pool supervisor error: "
                                 f"{type(exc).__name__}: {exc}")
            if self._slots[si].retired and self._other_live_slot(si):
                # leave the dispatch rotation to the live slots; the last
                # remaining supervisor keeps running so queued jobs still
                # terminate (as errors) instead of hanging
                break
        self._shutdown_slot(si)

    def _other_live_slot(self, si: int) -> bool:
        """Any slot besides `si` not permanently retired? Serialized so
        two concurrently-retiring slots cannot both defer to each other
        and leave the queue unsupervised."""
        with self._state:
            return any(not s.retired
                       for j, s in enumerate(self._slots) if j != si)

    # ---------------------------------------------------------- spawning
    def _worker_env(self) -> dict:
        from .. import resilience as rz
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = os.pathsep.join(
            [pkg_root] + [p for p in env.get("PYTHONPATH", "").split(
                os.pathsep) if p])
        # the supervisor's SIGKILL deadline replaces thread abandonment
        env["ABPOA_TPU_POOL_WORKER"] = "1"
        # injection budgets are brokered per job by the supervisor; the
        # raw env spec would re-arm a full budget in every worker
        env["ABPOA_TPU_INJECT"] = ""
        # the parent owns the archive records (exactly one per job)
        env["ABPOA_TPU_ARCHIVE"] = "0"
        # flight-recorder dumps land where the supervisor will harvest
        # them (obs/flight.py); pin the resolved default so parent and
        # worker can never disagree on the directory
        from ..obs import flight
        env.setdefault("ABPOA_TPU_FLIGHT_DIR", flight.flight_dir())
        # the parent already made the device decision this pool runs under
        env.setdefault("ABPOA_TPU_SKIP_PROBE", "1")
        env["ABPOA_TPU_RESILIENCE"] = "1" if rz.enabled() else "0"
        return env

    def _spawn(self, slot: _Slot) -> bool:
        """One spawn attempt: process, init frame, ready handshake."""
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "abpoa_tpu.parallel.pool_worker"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                env=self._worker_env(), bufsize=0)
        except OSError:
            return False
        slot.proc, slot.stdin, slot.stdout = proc, proc.stdin, proc.stdout
        slot.pid = proc.pid
        slot.spawned += 1
        if slot.spawned > 1:
            self._bump("restarts", "pool.restarts")
        try:
            write_frame(slot.stdin, {"params": self._params_blob,
                                     "label": self.label})
            deadline = time.monotonic() + spawn_timeout_s()
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise EOFError("ready handshake timed out")
                r, _, _ = select.select([slot.stdout], [], [],
                                        min(0.25, left))
                if self._aborting:
                    raise EOFError("pool aborted during spawn")
                if r:
                    frame = read_frame(slot.stdout)
                    if frame and frame[0] == "ready":
                        break
        except (EOFError, OSError, ValueError):
            self._kill_proc(slot)
            return False
        slot.ready = True
        self._publish_up()
        return True

    # consecutive failed spawn ATTEMPTS (no ready handshake ever) before a
    # slot is retired — a worker that can never start must surface as an
    # error on its jobs, not wedge the run in an infinite respawn loop
    MAX_SPAWN_FAILURES = 5

    def _ensure_worker(self, si: int,
                       max_attempts: Optional[int] = None) -> bool:
        """Live ready worker in slot `si`, spawning (with backoff) as
        needed. False when the pool is closing or the slot is RETIRED —
        permanently, after MAX_SPAWN_FAILURES consecutive spawns never
        reached a ready handshake (a worker command that cannot start
        must fast-fail its jobs, not stall every one of them through the
        full backoff ladder)."""
        if max_attempts is None:
            max_attempts = self.MAX_SPAWN_FAILURES
        slot = self._slots[si]
        if slot.retired:
            return False
        spawn_fails = 0
        while True:
            if self._aborting or self._closing:
                return False
            if slot.proc is not None and slot.proc.poll() is None \
                    and slot.ready:
                return True
            self._kill_proc(slot)
            if spawn_fails >= max_attempts:
                if max_attempts >= self.MAX_SPAWN_FAILURES:
                    with self._state:  # ordered vs _other_live_slot reads
                        slot.retired = True
                    from ..obs import record_fault
                    record_fault(
                        "worker_spawn_failed",
                        detail=f"slot {si}: retired after "
                               f"{spawn_fails} consecutive spawn "
                               "failures", action="slot_retired")
                return False
            delay = restart_backoff_s(slot.consec_deaths)
            deadline = time.monotonic() + delay
            while time.monotonic() < deadline:
                if self._aborting or self._closing:
                    return False
                time.sleep(min(0.1, deadline - time.monotonic()))
            if self._spawn(slot):
                return True
            spawn_fails += 1
            slot.consec_deaths += 1

    def _kill_proc(self, slot: _Slot) -> None:
        proc = slot.proc
        if proc is None:
            return
        try:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            pass
        for fp in (slot.stdin, slot.stdout):
            try:
                if fp:
                    fp.close()
            except OSError:
                pass
        slot.proc = slot.stdin = slot.stdout = None
        slot.ready = False
        self._publish_up()

    def _shutdown_slot(self, si: int) -> None:
        slot = self._slots[si]
        if slot.proc is None:
            return
        try:
            write_frame(slot.stdin, None)
            slot.proc.wait(timeout=10)
        except (OSError, ValueError, subprocess.TimeoutExpired):
            pass
        self._kill_proc(slot)

    # ---------------------------------------------------------- injection
    def _lease_kill(self, job: PoolJob) -> Optional[str]:
        """Worker-death injectors fire from the supervisor: consume one
        shot and bind remaining shots of the kind to this job's retries
        so `worker_sigsegv:2` crashes ONE job twice instead of two jobs
        once. The firing is COUNTED only when the death is observed
        (_execute's EOF path) — a tag whose dispatch frame never reached
        a worker is refunded, not fired."""
        from ..resilience import inject
        with self._state:
            if self._kill_bound is not None and self._kill_bound != job.id:
                return None
            for kind in inject.WORKER_KINDS:
                if inject.lease(kind, 1):
                    self._kill_bound = job.id
                    return kind
        return None

    def _unbind_kill(self, job: PoolJob) -> None:
        """Release the worker-kill binding when the bound job goes
        terminal: leftover shots (e.g. worker_sigsegv:3 after its victim
        was quarantined at 2 crashes) move on to the next job instead of
        stranding unfired."""
        with self._state:
            if self._kill_bound == job.id:
                self._kill_bound = None

    def _build_spec(self, job: PoolJob) -> str:
        """The injection spec THIS job's worker arms: unlimited kinds
        forwarded verbatim, count-limited kinds leased in full to one
        job at a time (single-process firing-order semantics: the first
        dispatchee consumes the budget, unfired shots are refunded on
        completion and migrate to a later job)."""
        from ..resilience import inject
        parts = []
        with self._state:
            for kind, left in inject.snapshot().items():
                if kind in inject.WORKER_KINDS:
                    continue
                if left == -1:
                    parts.append(kind)
                elif left > 0:
                    n = inject.lease(kind)
                    if n:
                        job.leases[kind] = n
                        parts.append(f"{kind}:{n}")
        return ",".join(parts)

    def _refund_leases(self, job: PoolJob,
                       fired: Optional[dict]) -> None:
        """Return the lease minus what actually fired. `fired=None` means
        the worker died mid-job: the shots are burned (refunding them
        could re-kill healthy jobs forever)."""
        from ..resilience import inject
        leases, job.leases = job.leases, {}
        if fired is None:
            return
        for kind, n in leases.items():
            used = int(fired.get(f"inject.{kind}", 0))
            inject.refund(kind, max(0, n - used))

    # ---------------------------------------------------------- merging
    def _merge_reads(self, records, agg: dict) -> None:
        """Fold one job's read-latency story into the parent: sketch
        buckets, backend/fallback attribution and the drop count cover
        every read; the raw records fill the parent's bounded list under
        its own cap. Same end state record_read would have produced had
        each read run in-process."""
        from ..obs import metrics, report
        from ..obs.report import READS_CAP
        rep = report()
        if not rep.enabled:
            return
        tmp = metrics.LogSketch()
        for i, c in (agg.get("counts") or {}).items():
            tmp.counts[int(i)] = int(c)
        tmp.count = int(agg.get("count") or 0)
        tmp.sum = float(agg.get("sum") or 0.0)
        if tmp.count:
            tmp.min = float(agg.get("min"))
            tmp.max = float(agg.get("max"))
        with metrics._MUT:
            if tmp.count:
                rep.wall_sketch.merge(tmp)
            for b, n in (agg.get("backends") or {}).items():
                rep.read_backends[b] = rep.read_backends.get(b, 0) + n
            for f, n in (agg.get("fallbacks") or {}).items():
                rep.read_fallbacks[f] = rep.read_fallbacks.get(f, 0) + n
            rep.reads_amortized += int(agg.get("amortized") or 0)
            for r in records:
                if len(rep.reads) < READS_CAP:
                    rep.reads.append(tuple(r))
                else:
                    rep.reads_dropped += 1
            rep.reads_dropped += int(agg.get("dropped") or 0)
        if metrics.enabled():
            metrics.publish_read_aggregate(agg.get("backends") or {},
                                           agg.get("fallbacks") or {},
                                           tmp)

    def _merge_extract(self, si: int, ext: dict,
                       job: Optional[PoolJob] = None,
                       t_dispatch: Optional[float] = None) -> None:
        """Fold one worker job's report delta into the parent report +
        fleet registry — the parent report is the one `--report`, the
        archive and the chaos assertions read, even when the breaker
        tripped inside a worker process. Shipped span deltas re-anchor on
        the parent-observed dispatch time and keep their (rid, attempt)
        tags, so a requeued job's two attempts render as distinct
        sub-trees of one request trace instead of conflating."""
        from ..obs import count, metrics, record_fault, record_read, report
        from ..obs import trace as _trace
        attempt = int(ext.get("attempt") or 0)
        if (job is not None and t_dispatch is not None
                and ext.get("spans") and _trace.enabled()):
            tr = _trace.tracer()
            wpid = self._slots[si].pid
            for kind, name, cat, rel, dur, args, req in ext["spans"]:
                tr.add_foreign(kind, name, cat, t_dispatch + rel, dur,
                               wpid, args, req)
            if ext.get("spans_dropped"):
                count("trace.worker_spans_dropped",
                      int(ext["spans_dropped"]))
        for name, v in (ext.get("counters") or {}).items():
            # faults.<kind> counters re-materialize via record_fault below
            if name.startswith("faults."):
                continue
            if isinstance(v, (int, float)) and v:
                count(name, v)
        for name, (w, c) in (ext.get("phases") or {}).items():
            report().merge_phase(name, w, c)
        for name, v in (ext.get("values") or {}).items():
            report().merge_value(name, *v)
        agg = ext.get("reads_agg")
        if agg is not None:
            # aggregate merge: sketch buckets + attribution cover EVERY
            # read of the job (a raw-record replay would undercount past
            # the worker's READS_CAP); the raw records only feed the
            # parent's bounded qlen/band attribution list
            self._merge_reads(ext.get("read_records") or [], agg)
        else:
            for r in ext.get("read_records") or []:
                # (wall_s, qlen, band_cols, backend, fallback, amortized)
                record_read(*r)
        for rec in ext.get("faults") or []:
            extra = {k: rec.get(k)
                     for k in ("request_id", "attempt", "dump")
                     if rec.get(k) is not None}
            # tag worker faults with the job's request context so a
            # requeued request's per-attempt fault records stay distinct
            if job is not None and job.rid:
                extra.setdefault("request_id", job.rid)
            if attempt:
                extra.setdefault("attempt", attempt)
            record_fault(rec.get("kind", "worker_fault"),
                         backend=rec.get("backend"),
                         set_index=rec.get("set"),
                         detail=rec.get("detail", ""),
                         action=rec.get("action", ""),
                         extra=extra or None)
        if ext.get("xla_compiles"):
            count("pool.worker_xla_compiles", int(ext["xla_compiles"]))
        if ext.get("cache_loads"):
            count("pool.worker_cache_loads", int(ext["cache_loads"]))
        new_deg = ext.get("degraded") or {}
        with self._state:
            old = self._slot_degraded.get(si, {})
            opened = [b for b in new_deg if b not in old]
            closed = [b for b in old if b not in new_deg]
            recloses = []
            for b in opened:
                self._deg_counts[b] = self._deg_counts.get(b, 0) + 1
            for b in closed:
                self._deg_counts[b] = self._deg_counts.get(b, 1) - 1
                if self._deg_counts[b] <= 0:
                    recloses.append(b)
            self._slot_degraded[si] = dict(new_deg)
        for b in opened:
            info = new_deg[b]
            report().mark_degraded(
                b, info.get("to", "?"),
                f"pool worker: {info.get('reason', 'breaker open')}",
                int(info.get("failures", 0)))
            if metrics.enabled():
                metrics.set_breaker_state(b, True)
        for b in recloses:
            report().mark_reclosed(b)
            if metrics.enabled():
                metrics.set_breaker_state(b, False)

    def _record_parent_spans(self, job: PoolJob, t_dispatch: float,
                             wpid: int, status: str = "ok") -> None:
        """Parent-side envelope spans for one dispatch attempt: the queue
        wait since submit and the attempt's pipe-to-pipe wall, tagged with
        the job's request id — the parent half of the cross-process tree
        (the worker half ships back as a span delta / flight dump)."""
        from ..obs import trace as _trace
        if not _trace.enabled():
            return
        req = (job.rid, job.attempts) if job.rid else None
        now = time.perf_counter()
        if job.attempts == 1:
            _trace.add_span("pool_wait", "pool", job.t_submit,
                            max(0.0, t_dispatch - job.t_submit),
                            args={"label": job.label}, req=req)
        _trace.add_span(f"pool_job:{job.kind}", "pool", t_dispatch,
                        now - t_dispatch,
                        args={"label": job.label, "worker": wpid,
                              "attempt": job.attempts, "status": status},
                        req=req)

    def _drop_slot_degraded(self, si: int) -> None:
        """A dead worker's breaker state dies with it."""
        from ..obs import metrics, report
        with self._state:
            old = self._slot_degraded.pop(si, {})
            recloses = []
            for b in old:
                self._deg_counts[b] = self._deg_counts.get(b, 1) - 1
                if self._deg_counts[b] <= 0:
                    recloses.append(b)
        for b in recloses:
            report().mark_reclosed(b)
            if metrics.enabled():
                metrics.set_breaker_state(b, False)

    # ---------------------------------------------------------- execution
    def _execute(self, si: int, job: PoolJob) -> None:
        from ..obs import record_fault
        slot = self._slots[si]
        if (job.deadline_ts is not None
                and time.monotonic() >= job.deadline_ts):
            # the wall budget expired while queued / between attempts:
            # terminal now — dispatching would only kill a healthy worker
            record_fault("job_deadline", detail=job.label,
                         action="expired_before_dispatch")
            self._unbind_kill(job)
            job.finish("timeout",
                       error=f"{job.label}: deadline expired before "
                             "dispatch")
            return
        if not self._ensure_worker(si):
            if self._closing or self._aborting:
                self._unbind_kill(job)
                job.finish("cancelled", error="pool closed before dispatch")
                return
            if self._slots[si].retired and self._other_live_slot(si):
                # a retired slot must not out-race healthy workers for the
                # queue: hand the job back (binding intact, no attempt
                # charged) — _supervise exits this slot's rotation next
                self._requeue_front(job)
                return
            # every slot is retired (or this is the only one): a worker
            # that can never start is a real bug — surface it, don't hang
            self._unbind_kill(job)
            record_fault("worker_spawn_failed", detail=job.label,
                         action="propagated")
            job.finish("error",
                       error=f"pool worker failed to start "
                             f"({self.MAX_SPAWN_FAILURES} attempts)")
            return
        job.attempts += 1
        kill_kind = self._lease_kill(job)
        spec = self._build_spec(job)
        # request context crosses the pipe with the dispatch frame; the
        # parent-observed dispatch time anchors the worker's shipped span
        # delta on this timeline
        meta = {"rid": job.rid, "attempt": job.attempts,
                "trace": job.trace, "label": job.label}
        t_dispatch = time.perf_counter()
        try:
            write_frame(slot.stdin,
                        ("job", job.id, job.kind, job.payload, spec,
                         kill_kind, meta))
        except (OSError, ValueError):
            # the worker died while IDLE: not this job's doing — no
            # attempt charged, leases refunded, straight back to the front
            self._note_death(si, None)
            job.attempts -= 1
            self._refund_leases(job, fired={})
            if kill_kind:
                from ..resilience import inject
                with self._state:  # the kill tag never reached a worker
                    self._kill_bound = None
                    inject.refund(kill_kind, 1)
            self._requeue_front(job)
            return
        if job.deadline_ts is not None:
            deadline_ts = job.deadline_ts     # wall budget from submit
            deadline = job.deadline_s
        else:
            deadline = self._default_deadline
            deadline_ts = (time.monotonic() + deadline
                           if deadline > 0 else None)
        limit = rss_limit_bytes(job.est_bytes)
        last_beat = time.monotonic()
        while True:
            now = time.monotonic()
            if self._aborting:
                self._kill_proc(slot)
                self._refund_leases(job, fired=None)
                self._unbind_kill(job)
                job.finish("cancelled", error="pool aborted")
                return
            if deadline_ts is not None and now >= deadline_ts:
                self._hard_kill(si, job, "deadline",
                                f"no result within {deadline:.1f}s job "
                                "deadline (hard SIGKILL replaces thread "
                                "abandonment)")
                # the budget is spent: terminal, same contract as a
                # watchdog DispatchTimeout (hangs are not retryable).
                # The lease dies with the worker (fired counts unknowable)
                self._refund_leases(job, fired=None)
                self._unbind_kill(job)
                self._record_parent_spans(job, t_dispatch, slot.pid,
                                          status="killed_deadline")
                job.finish("timeout",
                           error=f"{job.label}: killed at the "
                                 f"{deadline:.1f}s job deadline")
                return
            if self._stall and now - last_beat > self._stall:
                self._hard_kill(si, job, "stall",
                                f"heartbeat silent for {self._stall:.1f}s")
                # burn the lease: what fired in the stalled worker is
                # unknowable, and a refund could re-kill healthy jobs
                self._refund_leases(job, fired=None)
                self._record_parent_spans(job, t_dispatch, slot.pid,
                                          status="killed_stall")
                self._after_death(job, "stalled heartbeat")
                return
            tick = 0.25 if deadline_ts is None else min(
                0.25, max(0.01, deadline_ts - now))
            try:
                r, _, _ = select.select([slot.stdout], [], [], tick)
            except (OSError, TypeError, ValueError):
                # closed/None stdout (concurrent _kill_proc): fall through
                # to read_frame, whose death path owns the cleanup
                r = [slot.stdout]
            if not r:
                continue
            try:
                frame = read_frame(slot.stdout)
            except (EOFError, OSError, ValueError, AttributeError):
                if kill_kind:
                    # the injected death happened: counted at observation
                    # (the worker cannot count its own SIGKILL)
                    from ..obs import count
                    count(f"inject.{kill_kind}")
                self._note_death(si, job)
                self._refund_leases(job, fired=None)
                self._record_parent_spans(job, t_dispatch, slot.pid,
                                          status="worker_died")
                self._after_death(job, "worker died mid-job")
                return
            last_beat = time.monotonic()
            tag = frame[0]
            if tag == "hb":
                slot.rss = int(frame[2] or 0)
                if limit and slot.rss > limit:
                    self._hard_kill(
                        si, job, "rss",
                        f"worker RSS {slot.rss} B over the "
                        f"{limit} B budget")
                    # same burn as every worker death: fired unknowable
                    self._refund_leases(job, fired=None)
                    self._record_parent_spans(job, t_dispatch, slot.pid,
                                              status="killed_rss")
                    self._after_death(job, "RSS budget exceeded")
                    return
                continue
            if tag == "ok" and frame[1] == job.id:
                result = frame[2] or {}
                extract = result.pop("extract", None)
                if extract:
                    self._merge_extract(si, extract, job=job,
                                        t_dispatch=t_dispatch)
                self._record_parent_spans(job, t_dispatch, slot.pid)
                self._refund_leases(
                    job, fired=(extract or {}).get("counters") or {})
                self._unbind_kill(job)
                slot.consec_deaths = 0
                self._bump("jobs", "pool.jobs")
                job.finish("ok", result=result)
                return
            if tag == "err" and frame[1] == job.id:
                # firings before the failure are unknowable: burn the
                # lease rather than risk re-firing consumed shots
                self._refund_leases(job, fired=None)
                self._unbind_kill(job)
                slot.consec_deaths = 0
                # a worker-side 500 is exactly what `why` exists for:
                # its trace must still carry the dispatch envelope
                self._record_parent_spans(job, t_dispatch, slot.pid,
                                          status="error")
                record_fault("worker_error", detail=str(frame[2])[:300],
                             action="propagated",
                             extra={"request_id": job.rid or None,
                                    "attempt": job.attempts})
                job.finish("error", error=str(frame[2]))
                return
            # unknown/stale frame: drop it, keep watching

    def _harvest_dump(self, si: int, job: Optional[PoolJob], reason: str,
                      detail: str) -> Optional[str]:
        """Collect the dead worker's flight-recorder dump (obs/flight.py):
        the supervisor enriches it with the observed cause of death —
        the worker cannot record its own SIGKILL — and attaches the path
        to the job so the archive record (and `abpoa-tpu why`) can find
        it. Never fails the containment path."""
        from ..obs import flight
        slot = self._slots[si]
        if not slot.pid:
            return None
        try:
            dest = flight.harvest(slot.pid, reason,
                                  rid=(job.rid if job else ""),
                                  attempt=(job.attempts if job else 0),
                                  detail=detail)
        except Exception:  # noqa: BLE001 — harvest must never kill the pool
            return None
        if dest:
            self._bump("flight_dumps", "pool.flight_dumps")
            if job is not None:
                job.dumps.append(dest)
        return dest

    def _hard_kill(self, si: int, job: PoolJob, why: str,
                   detail: str) -> None:
        from ..obs import record_fault
        slot = self._slots[si]
        self._bump("kills", "pool.kills")
        slot.consec_deaths += 1
        self._kill_proc(slot)
        # harvest AFTER the kill: the dump on disk is final (no concurrent
        # writer), at most one heartbeat stale
        dump = self._harvest_dump(si, job, f"killed_{why}", detail)
        record_fault("worker_killed", set_index=None,
                     detail=f"{job.label}: {detail}", action=f"kill_{why}",
                     extra={"request_id": job.rid or None,
                            "attempt": job.attempts, "dump": dump})
        self._drop_slot_degraded(si)

    def _note_death(self, si: int, job: Optional[PoolJob]) -> None:
        """A worker died on its own (signal, unexpected exit)."""
        from ..obs import record_fault
        slot = self._slots[si]
        rc = None
        if slot.proc is not None:
            try:
                rc = slot.proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                pass
        desc = f"exit {rc}"
        if rc is not None and rc < 0:
            try:
                desc = f"signal {signal.Signals(-rc).name}"
            except ValueError:
                desc = f"signal {-rc}"
        self._bump("crashes", "pool.worker_crashes")
        dump = self._harvest_dump(si, job, "crashed",
                                  f"worker pid {slot.pid} died ({desc})")
        record_fault("worker_crash",
                     detail=(f"{job.label}: " if job else "")
                     + f"worker pid {slot.pid} died ({desc})",
                     action="respawn",
                     extra={"request_id": (job.rid or None) if job else None,
                            "attempt": job.attempts if job else None,
                            "dump": dump})
        slot.consec_deaths += 1
        self._kill_proc(slot)
        self._drop_slot_degraded(si)

    def _after_death(self, job: PoolJob, why: str) -> None:
        """Exactly-once requeue: first death retries on a fresh worker,
        the second quarantines the job as poison."""
        from ..obs import count, record_fault
        if job.attempts >= 2:
            self._unbind_kill(job)
            self._bump("poison_jobs", "pool.poison_jobs")
            count("quarantine.sets")
            record_fault("poison_job",
                         detail=f"{job.label}: {why} on attempt "
                                f"{job.attempts}; quarantined",
                         action="quarantined")
            print(f"Warning: pool job {job.label!r} killed its worker "
                  f"{job.attempts} times ({why}); quarantined as a "
                  "poison job.", file=sys.stderr)
            job.finish("poison", error=f"{why} (x{job.attempts})")
            return
        self._bump("requeues", "pool.requeues")
        self._requeue_front(job)


# --------------------------------------------------------------------------- #
# the `-l` batch runner                                                       #
# --------------------------------------------------------------------------- #

def _archive_job(job: PoolJob, abpt, status: str) -> None:
    """One archive record per job TERMINAL status (idempotent across
    requeues by construction: only the terminal write exists) — the
    window `abpoa-tpu slo` evaluates, same field shapes as the serve
    per-request records. The record cross-references the job's request
    id and harvested flight dump, so `slo` offenders and `abpoa-tpu why`
    can walk from a burned budget to the artifact that explains it."""
    from .. import obs
    rec = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "kind": "pool_job",
        "label": job.label,
        "request_id": job.rid or None,
        "device": abpt.device,
        "status": status,
        "attempts": job.attempts,
        "total_wall_s": round(job.wall_s(), 6),
        "reads": 0,
        "faults": 1 if status != "ok" else 0,
        "quarantined": 1 if status != "ok" else 0,
    }
    if job.dumps:
        rec["dump_file"] = job.dumps[-1]
    obs.archive.append_record(rec)


def run_pool_batch(files: Sequence[str], abpt, out_fp: IO[str],
                   n_workers: int) -> dict:
    """The pool `-l` runner: one job per read-set file, fanned over
    supervised worker processes, outputs emitted in file order so the
    bytes match sequential processing exactly. Returns the same
    {"sets", "quarantined"} stats dict as the serial runner (plus
    "cancelled" after a SIGTERM drain)."""
    from ..obs import count, metrics, observe
    stats = {"sets": len(files), "quarantined": 0}
    if not (abpt.out_msa or abpt.out_cons or abpt.out_gfa):
        return stats  # mirror msa_from_file: nothing to emit or compute
    pool = WorkerPool(n_workers, abpt, label="batch")
    count("pool.runs")
    observe("pool.workers", pool.n_workers)
    metrics.publish_batch_progress(0, total=len(files))
    # every `-l` set under --workers gets a request id at ingress (the
    # PR-15 propagation contract): worker span deltas merge back under it
    # when the run traces, and the archive/dump records carry it always
    from ..obs import trace as _trace
    jobs = []
    for i, fn in enumerate(files):
        rid = _trace.new_request_id()
        jobs.append(pool.submit(
            "file", (i, fn), label=fn, rid=rid,
            trace=_trace.enabled() and _trace.sampled(rid)))
    # graceful drain on SIGTERM: queued jobs are cancelled, in-flight
    # jobs finish, completed output is emitted, rc stays 0 (main-thread
    # CLI runs only; library callers keep their own signal handling)
    drained = {"hit": False}
    old_handler = None
    in_main = threading.current_thread() is threading.main_thread()
    if in_main:
        def _on_term(signum, _frame):
            drained["hit"] = True
            pool.drain_intake()
        try:
            old_handler = signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            old_handler = None
    try:
        pool.start()
        for job in jobs:
            job.done.wait()
            metrics.bump_batch_set_done()
            if job.status == "ok":
                # quarantined-in-worker sets may carry partial output,
                # exactly like the serial runner writing directly into
                # out_fp when the exception interrupts it
                out_fp.write(job.result.get("text", ""))
                try:
                    # stream per-set: a consumer (or the drain test)
                    # sees each set as it completes, not at close
                    out_fp.flush()
                except (AttributeError, OSError):
                    pass
                if job.result.get("quarantined"):
                    stats["quarantined"] += 1
                    _archive_job(job, abpt, "quarantined")
                else:
                    _archive_job(job, abpt, "ok")
            elif job.status in ("poison", "timeout"):
                stats["quarantined"] += 1
                _archive_job(job, abpt, job.status)
            elif job.status == "cancelled":
                stats["cancelled"] = stats.get("cancelled", 0) + 1
            else:  # "error": an unclassified worker failure is a real bug
                raise PoolWorkerError(
                    f"pool worker failed on {job.label!r}: {job.error}")
            # emitted and archived: release the set's output text now —
            # holding every result until close would grow parent RSS with
            # the whole batch's output while each worker stays in budget
            job.result = {}
    finally:
        pool.close(graceful=True)
        if in_main and old_handler is not None:
            try:
                signal.signal(signal.SIGTERM, old_handler)
            except (ValueError, OSError):
                pass
    if drained["hit"]:
        print(f"[abpoa_tpu::pool] SIGTERM drain: "
              f"{stats.get('cancelled', 0)} queued sets cancelled, "
              "in-flight sets finished, completed output emitted.",
              file=sys.stderr)
    return stats


def run_hybrid_batch(files: Sequence[str], abpt, out_fp: IO[str],
                     n_workers: int, k_cap: int) -> dict:
    """The hybrid `-l` runner (scheduler route "hybrid"): the file list
    splits into contiguous groups of `k_cap` sets, each group executes as
    ONE pool job running the split-lockstep driver inside its worker
    (parallel/lockstep.py), and outputs are emitted in file order — the
    pool's containment (hard-kill deadlines, crash requeue, poison
    quarantine) wraps whole groups instead of single sets."""
    from ..obs import count, metrics, observe
    stats = {"sets": len(files), "quarantined": 0}
    if not (abpt.out_msa or abpt.out_cons or abpt.out_gfa):
        return stats
    groups = [list(enumerate(files))[i:i + k_cap]
              for i in range(0, len(files), max(1, k_cap))]
    pool = WorkerPool(n_workers, abpt, label="hybrid")
    count("pool.runs")
    observe("pool.workers", pool.n_workers)
    metrics.publish_batch_progress(0, total=len(files))
    # a group job is len(grp) sets' worth of work: scale the hard-kill
    # deadline accordingly, or a healthy k_cap-set group would be killed
    # at the single-set budget
    base_deadline = job_deadline_s()
    from ..obs import trace as _trace
    jobs = []
    for grp in groups:
        rid = _trace.new_request_id()
        jobs.append(pool.submit(
            "group", grp, label=f"group[{grp[0][0]}..{grp[-1][0]}]",
            deadline_s=(base_deadline * len(grp)
                        if base_deadline > 0 else None),
            rid=rid, trace=_trace.enabled() and _trace.sampled(rid)))
    # graceful SIGTERM drain, same contract as run_pool_batch: queued
    # groups cancel, in-flight groups finish, completed output is
    # emitted, rc stays 0 (main-thread CLI runs only)
    drained = {"hit": False}
    old_handler = None
    in_main = threading.current_thread() is threading.main_thread()
    if in_main:
        def _on_term(signum, _frame):
            drained["hit"] = True
            pool.drain_intake()
        try:
            old_handler = signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            old_handler = None
    try:
        pool.start()
        for grp, job in zip(groups, jobs):
            job.done.wait()
            # every set of the group reaches a terminal disposition here
            # (emitted, quarantined, or cancelled): the batch moved past
            # it either way — same 'done' definition as run_pool_batch
            for _ in grp:
                metrics.bump_batch_set_done()
            if job.status == "ok":
                texts = job.result.get("texts", {})
                quar = set(job.result.get("quarantined", ()))
                for idx, _fn in grp:
                    out_fp.write(texts.get(idx, ""))
                stats["quarantined"] += len(quar)
                _archive_job(job, abpt,
                             "quarantined" if quar else "ok")
                try:
                    out_fp.flush()
                except (AttributeError, OSError):
                    pass
            elif job.status in ("poison", "timeout"):
                # a whole group quarantined: the containment unit of the
                # hybrid route is the group
                stats["quarantined"] += len(grp)
                _archive_job(job, abpt, job.status)
            elif job.status == "cancelled":
                stats["cancelled"] = stats.get("cancelled", 0) + len(grp)
            else:
                raise PoolWorkerError(
                    f"hybrid group failed on {job.label!r}: {job.error}")
            job.result = {}
    finally:
        pool.close(graceful=True)
        if in_main and old_handler is not None:
            try:
                signal.signal(signal.SIGTERM, old_handler)
            except (ValueError, OSError):
                pass
    if drained["hit"]:
        print(f"[abpoa_tpu::pool] SIGTERM drain: "
              f"{stats.get('cancelled', 0)} queued sets cancelled, "
              "in-flight groups finished, completed output emitted.",
              file=sys.stderr)
    return stats
