"""Map driver: stream reads against ONE static graph — zero fusion barrier.

The split consensus driver (parallel/lockstep.py) interleaves host fusion
with each batched DP round because every lane's graph grows; a restored
read-only graph deletes that tax entirely. This driver holds ONE cached
`StaticGraphTables` (align/dp_chunk.py — graph half built once, query half
stamped per read) and runs exactly one vmapped `run_dp_chunk` round per
read batch:

- every lane RETIRES at the end of every round (one read = one round, no
  multi-round residency), so every round boundary is a join point — lane
  occupancy under a saturated stream is limited only by arrival, not by
  the consensus path's drain tails (the 0.844 PERF.md round 17 measured);
- R and P are CONSTANT for the graph's lifetime (`StaticGraphTables.R`/
  `.P`), so a warmed (R, Qp, W, K) signature serves the whole stream —
  the map gate's zero-compile-miss claim;
- results are per-read `(AlignResult, strand)` pairs (GAF material, io/
  gaf.py), never consensus: the graph is NEVER mutated (asserted by the
  restore→map→restore round-trip test);
- amb-strand rescue is the same second batched dispatch as the consensus
  driver: sub-threshold forward scores replay their reverse complement
  against the SAME graph tables, best score wins, strand "-" records it;
- a device backtrack divergence falls back to the per-read numpy oracle
  (`fallback.map_bt_err`) instead of a sequential re-run of a whole set —
  map lanes are single reads, so the fallback is one host alignment.

Byte parity: per read this is the oracle's whole-graph global alignment
(same tables, same band, same rc threshold), so GAF records are
byte-identical to the host oracle for any K and any join schedule.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import constants as C
from ..params import Params

MAX_W_GROWTH = 6


class MapHook:
    """Round-boundary streaming protocol for `map_reads_split`.

    ``on_round(round_i, free_slots)`` is called before each round and
    returns up to ``free_slots`` joiners as ``(rid, query)`` tuples
    (encoded np arrays). Off-rung joiners (qlen + 2 > Qp) are rejected via
    ``on_retire(rid, None, round_i)`` — the hook owns answering them.

    ``on_retire(rid, outcome, round_i)`` delivers one read's terminal
    result the round it ran: ``(AlignResult, strand, fallback_reason)``
    with strand "+"/"-" and fallback_reason None or "map_bt_err" (the
    numpy-oracle rescue), or ``None`` for an off-rung rejection.
    """

    def on_round(self, round_i: int, free_slots: int) -> list:
        return []

    def on_retire(self, rid, outcome, round_i: int) -> None:  # pragma: no cover
        pass


def load_static_graph(path: str, abpt: Params):
    """Restore a GFA/MSA graph from `path` (io/restore.py — same ingest as
    `-i`) and wrap it in `StaticGraphTables`: THE setup step shared by
    `abpoa-tpu map`, the serve `/map` registry and the gates. Returns
    ``(ab, static)``; raises ValueError when the file restores nothing."""
    from ..align.dp_chunk import StaticGraphTables
    from ..io.restore import restore_graph
    from ..pipeline import Abpoa
    ab = Abpoa()
    abpt.incr_fn = path
    restore_graph(ab, abpt)
    if ab.n_seq == 0 or ab.graph.node_n <= 2:
        raise ValueError(f"no graph restored from {path!r} "
                         "(expected abPOA GFA S/P lines or an MSA FASTA)")
    return ab, StaticGraphTables(ab.graph, abpt)


def map_read_host(g, abpt: Params, q: np.ndarray):
    """Per-read host mapping — THE serial baseline and byte-parity oracle
    (map_gate's A side, the CLI's no-accelerator route, the bt_err
    fallback's contract): one whole-graph numpy alignment plus the same
    amb-strand rc rescue rule as the batched driver. Returns
    ``(AlignResult, strand)``."""
    from ..align.oracle import align_sequence_to_subgraph_numpy
    from ..pipeline import _rc_encode
    res = align_sequence_to_subgraph_numpy(
        g, abpt, C.SRC_NODE_ID, C.SINK_NODE_ID, q)
    strand = "+"
    if abpt.amb_strand:
        thr = min(len(q), g.node_n - 2) * abpt.max_mat * 0.3333
        if res.best_score < thr:
            rc = align_sequence_to_subgraph_numpy(
                g, abpt, C.SRC_NODE_ID, C.SINK_NODE_ID, _rc_encode(q))
            if rc.best_score > res.best_score:
                res, strand = rc, "-"
    return res, strand


def _stamp_rc(tables: dict, abpt: Params, rc_q: np.ndarray) -> dict:
    """Re-stamp one lane's table dict with the reverse complement (copy —
    the shared graph arrays stay untouched)."""
    t = dict(tables)
    qp = np.zeros_like(t["qp"])
    query_pad = np.zeros_like(t["query"])
    if len(rc_q):
        qp[:, 1: len(rc_q) + 1] = abpt.mat[:, rc_q]
        query_pad[:len(rc_q)] = rc_q
    t["qp"] = qp
    t["query"] = query_pad
    return t


def map_reads_split(static, queries: Sequence[np.ndarray], abpt: Params,
                    k_cap: Optional[int] = None,
                    hook: Optional[MapHook] = None,
                    Qp: Optional[int] = None,
                    mesh=None) -> list:
    """Map `queries` (plus any hook-streamed joiners) against the static
    graph in vmapped pow2 read batches of up to `k_cap` lanes.

    Returns one ``(AlignResult, strand, fallback_reason)`` triple per
    initial query, in order. Hook joiners are answered exclusively through
    ``hook.on_retire``. `Qp` pins the group's query rung (serve groups);
    by default it is planned from the longest initial query.

    `mesh` (a jax Mesh) shards each round's single dispatch over the lane
    mesh: the graph tables replicate, the read batch splits, and the
    default `k_cap` prices the whole mesh (mesh x the per-chip cap). Join
    semantics are unchanged — every round boundary is still a join point.
    """
    from .. import obs
    from ..align.dp_chunk import (chunk_plane16, dispatch_dp_chunk,
                                  result_from_chunk)
    from ..align.oracle import align_sequence_to_subgraph_numpy
    from ..compile.ladder import k_rung, plan_chunk_buckets, qp_rung
    from ..obs import metrics
    from ..pipeline import _band_cols, _rc_encode
    from . import scheduler
    from .shard import mesh_size

    S = mesh_size(mesh)
    occ_route = "sharded" if S > 1 else "map"
    if Qp is None:
        qmax0 = max((len(q) for q in queries), default=1)
        Qp = qp_rung(qmax0)
    _qp, W, _local = plan_chunk_buckets(abpt, Qp - 2)
    if k_cap is None:
        from .runner import lockstep_group_size
        per_chip = scheduler.noop_k_cap(lockstep_group_size(),
                                        route=occ_route)
        k_cap = per_chip * max(S, 1)
    k_cap = max(1, int(k_cap))
    amb = bool(abpt.amb_strand)
    g = static.graph
    R, P = static.R, static.P
    plane16 = chunk_plane16(abpt, Qp - 2, static.n_rows)
    thr_base = abpt.max_mat * 0.3333

    # pending initial reads feed lanes exactly like hook joiners: the
    # driver is one stream, arrival order preserved
    pending: List[Tuple[int, np.ndarray]] = list(enumerate(queries))
    final: dict = {}

    def retire(rid, outcome, round_i: int) -> None:
        if isinstance(rid, int) and 0 <= rid < len(queries):
            final[rid] = outcome
        if hook is not None:
            hook.on_retire(rid, outcome, round_i)

    round_i = 0
    while True:
        # board: pending initial reads first, then hook joiners into the
        # remaining free slots — every slot is free every round (zero
        # fusion barrier: no lane survives a round)
        lanes: List[Tuple[object, np.ndarray]] = []
        while pending and len(lanes) < k_cap:
            rid, q = pending.pop(0)
            if len(q) + 2 > Qp:
                # oversized initial read: same off-rung contract as a
                # joiner — reject, never force a new Qp rung
                retire(rid, None, round_i + 1)
                continue
            lanes.append((rid, q))
        if hook is not None:
            joiners = hook.on_round(round_i + 1, k_cap - len(lanes))
            for rid, q in joiners or ():
                if len(q) + 2 > Qp or len(lanes) >= k_cap:
                    retire(rid, None, round_i + 1)
                    continue
                lanes.append((rid, q))
                obs.count("map.joins")
        if not lanes:
            break
        round_i += 1
        t_round = time.perf_counter()
        obs.rounds.begin_round()
        obs.count("map.rounds")
        occ = len(lanes) / k_cap
        scheduler.observe_lane_occupancy(occ, route=occ_route)
        metrics.publish_map_round(len(lanes), occ)

        with obs.phase("align"):
            tables = []
            for _rid, q in lanes:
                obs.record_dp(static.n_rows, _band_cols(abpt, len(q)),
                              abpt.gap_mode)
                tables.append(static.tables_for(q, Qp))
            Kb = k_rung(len(lanes), S)
            # W-growth retry wraps BOTH strand dispatches, same contract
            # as the consensus driver: an overflowed result never escapes
            results: list = []
            for _g in range(MAX_W_GROWTH + 1):
                packed = dispatch_dp_chunk(abpt, tables, Kb, R, P, Qp, W,
                                           plane16, mesh=mesh)
                results = [
                    result_from_chunk(abpt, packed[i], tables[i],
                                      static.idx2nid) + ("+",)
                    for i in range(len(lanes))]
                overflowed = any(f["overflow"] for _res, f, _s in results)
                if amb and not overflowed:
                    rc_is = []
                    for i, (_rid, q) in enumerate(lanes):
                        res, _f, _s = results[i]
                        thr = min(len(q), g.node_n - 2) * thr_base
                        if res.best_score < thr:
                            rc_is.append(i)
                    if rc_is:
                        rc_tables = []
                        for i in rc_is:
                            rc_q = _rc_encode(lanes[i][1])
                            obs.record_dp(static.n_rows,
                                          _band_cols(abpt, len(rc_q)),
                                          abpt.gap_mode)
                            rc_tables.append(_stamp_rc(tables[i], abpt,
                                                       rc_q))
                        rc_packed = dispatch_dp_chunk(abpt, rc_tables, Kb,
                                                      R, P, Qp, W, plane16,
                                                      mesh=mesh)
                        for j, i in enumerate(rc_is):
                            rc_res, rc_f = result_from_chunk(
                                abpt, rc_packed[j], rc_tables[j],
                                static.idx2nid)
                            if rc_f["overflow"]:
                                overflowed = True
                            elif rc_f["bt_err"]:
                                results[i] = (results[i][0],
                                              {"overflow": False,
                                               "bt_err": True}, "+")
                            elif (rc_res.best_score
                                  > results[i][0].best_score):
                                results[i] = (rc_res, rc_f, "-")
                if not overflowed:
                    break
                W *= 2
                obs.count("fused.grow.band")
            else:
                raise RuntimeError(
                    "map driver: band growth did not converge")

        n_done = 0
        for i, (rid, q) in enumerate(lanes):
            res, f, strand = results[i]
            fallback = None
            if f["bt_err"]:
                # single-read lane: the numpy oracle IS the sequential
                # re-run — one host alignment, counted as a fallback
                obs.count("fallback.map_bt_err")
                fallback = "map_bt_err"
                oq = _rc_encode(q) if strand == "-" else q
                res = align_sequence_to_subgraph_numpy(
                    g, abpt, C.SRC_NODE_ID, C.SINK_NODE_ID, oq)
            retire(rid, (res, strand, fallback), round_i)
            n_done += 1
        obs.count("map.reads", n_done)
        wall = time.perf_counter() - t_round
        obs.rounds.record_round(occ_route, len(lanes), k_cap, wall, mesh=S)
        share = wall / max(n_done, 1)
        for _rid, q in lanes:
            obs.record_read(share, len(q), _band_cols(abpt, len(q)),
                            abpt.device, amortized=True,
                            fallback=None)

    return [final.get(rid) for rid in range(len(queries))]
