"""Multi-device scaling: shard independent read sets across a TPU mesh.

The POA algorithm needs no cross-chip collectives (SURVEY.md §2.3): the unit of
work "align read set -> call consensus" fits one chip, so fleet scaling is data
parallelism over read sets (the reference's `-l` file-list mode,
/root/reference/src/abpoa.c:148-168). Three layers:

- lockstep batching (`_lockstep_compute`): K read sets advance through the
  fused progressive loop as ONE vmapped dispatch per device
  (fused_loop.progressive_poa_fused_batch) — the per-chip throughput lever:
  each sequential graph-row step now carries K sets' worth of work.
- `run_batch`: the `-l` product path. Uses lockstep groups when the config is
  in fused-loop scope, else round-robins files over local devices with each
  set's DP kernels placed via `jax.default_device`.
- `shard_dp_batch`: a `shard_map`-over-Mesh batched DP step — many same-bucket
  alignments at once, one per mesh slot. Building block for multi-host DCN
  fan-out, where each host feeds its local mesh slice.
"""
from __future__ import annotations

import os
import sys
import time
from typing import IO, List, Sequence

import numpy as np

from ..params import Params

# jax is imported lazily inside each entry point: a host-only `-l` run
# (device numpy/native) must not pay the jax import, and the CLI routes
# every file list through run_batch


def lockstep_group_size() -> int:
    """Sets per lockstep dispatch. Shared static buckets mean K sets cost
    K x the largest set's plane memory; 8 fits comfortably in 16 GB HBM for
    the north-star workload (500 reads x 10 kb: ~45 MB of planes + graph
    arrays per set at W=4096). Override via ABPOA_TPU_LOCKSTEP_K; 1
    disables grouping (sets still run the fused loop, one per dispatch)."""
    return max(1, int(os.environ.get("ABPOA_TPU_LOCKSTEP_K", "8")))


def lockstep_enabled(abpt: Params) -> bool:
    """Should `-l`/batch runs vmap K sets into one lockstep dispatch?

    On CPU-only hosts the answer is NO by default: the round-8 measurement
    (ROUND8_NOTES.md, BENCH_lockstep_cpu.json) showed K=4 lockstep 1.37x
    SLOWER than the serial K=1 path on the 8-way CPU mesh — vmapped masked
    scatters serialize on XLA:CPU, so batching independent sets loses a
    third of the machine. Lockstep therefore defaults on only when a real
    accelerator mesh is attached, and stays available as an explicit
    opt-in (`--lockstep on` / ABPOA_TPU_LOCKSTEP=1) for measurement.
    """
    mode = getattr(abpt, "lockstep", "auto")
    if mode == "on":
        return True
    if mode == "off":
        return False
    env = os.environ.get("ABPOA_TPU_LOCKSTEP", "").lower()
    if env in ("1", "on", "true"):
        return True
    if env in ("0", "off", "false"):
        return False
    from ..utils.probe import has_accelerator
    return has_accelerator()


def _default_device(dev):
    """jax.default_device when a device was picked, no-op otherwise (the
    split driver and hybrid workers run on the process default)."""
    if dev is None:
        import contextlib
        return contextlib.nullcontext()
    import jax
    return jax.default_device(dev)


def _lockstep_ok(abpt: Params) -> bool:
    from ..pipeline import plain_route
    from ..align.eligibility import fused_config_eligible
    return (abpt.device in ("jax", "tpu", "pallas")
            and not abpt.incr_fn
            and lockstep_enabled(abpt)
            and plain_route(abpt)
            and fused_config_eligible(abpt))


def flush_lockstep_group(group: List, abpt: Params, devices: List,
                         gi: int, impl: str = None, mesh=None) -> dict:
    """Run one lockstep group of (idx, ab, seqs, weights) entries; returns
    {idx: Abpoa-with-finished-graph}. Entries absent from the result
    (whole-batch failure, or a per-set device failure) take the sequential
    path. Shared by the `-l` batch segments below and the serve
    coalescer (abpoa_tpu/serve): both pack same-rung read sets into one
    vmapped dispatch per group.

    impl selects the lockstep implementation (scheduler.lockstep_impl
    when None): "device" = the all-device vmapped fused loop (real
    accelerator mesh), "split" = host fusion + batched banded-DP rounds
    (parallel/lockstep.py — CPU hosts). `mesh` (split impl only) shards
    each round's dispatch over a device mesh (the scheduler's "sharded"
    route)."""
    if not group:
        return {}
    from ..align.fused_loop import (partition_by_length_bucket,
                                    progressive_poa_fused_batch)
    from ..obs import count, device_capture, observe, trace
    from . import scheduler
    from .lockstep import progressive_poa_split_batch
    if impl is None:
        impl = scheduler.lockstep_impl(abpt)
    count("lockstep.groups")
    observe("lockstep.group_size", len(group))
    results: dict = {}
    dev = devices[gi % len(devices)] if devices else None
    outs = []
    flat = []
    # same-Qp-bucket sub-batches keep the shared padding honest (a 100 bp
    # set must not pay a 10 kb set's planes); a failed bucket falls back
    # alone — completed buckets keep their device results. The outer
    # device_capture makes the whole group ONE XProf capture (the inner
    # per-sub-batch brackets degrade to trace annotations inside it).
    from .. import resilience as rz
    backend = "jax" if abpt.device == "tpu" else abpt.device
    with trace.span("lockstep_group", "fused",
                    args={"k": len(group), "group": gi, "impl": impl}), \
            device_capture("lockstep_group"):
        for sub in partition_by_length_bucket(
                [(e[0], e[2], e[3], e[1]) for e in group]):
            # memory admission from the compile-ladder rung: an over-budget
            # group dispatches in smaller K pieces; sets too large for even
            # a K=1 dispatch demote to the sequential path (counted +
            # reported by admission_plan)
            pieces = (rz.memory.admission_plan(abpt, sub, lambda e: e[1])
                      if rz.enabled() else [(list(sub), "dispatch")])
            for piece, action in pieces:
                flat.extend(piece)
                if action == "demote":
                    count("fallback.admission_demote", len(piece))
                    outs.extend([None] * len(piece))
                    continue
                t0 = time.perf_counter()
                try:
                    with _default_device(dev):
                        if impl == "split":
                            # the split driver times its own align/fusion
                            # phases and per-read records (phases are a
                            # partition of wall time by convention)
                            outs.extend(rz.guarded_device_call(
                                "lockstep_batch", backend,
                                lambda p=piece:
                                progressive_poa_split_batch(
                                    [e[1] for e in p], [e[2] for e in p],
                                    abpt, mesh=mesh)))
                        else:
                            from ..obs import phase
                            with phase("align_fused"):
                                outs.extend(rz.guarded_device_call(
                                    "lockstep_batch", backend,
                                    lambda p=piece:
                                    progressive_poa_fused_batch(
                                        [e[1] for e in p], [e[2] for e in p],
                                        abpt)))
                except (rz.DispatchFailed, RuntimeError) as e:
                    print(f"Warning: fused lockstep batch failed ({e}); "
                          "falling back to sequential processing.",
                          file=sys.stderr)
                    count("fallback.lockstep_to_sequential")
                    outs.extend([None] * len(piece))
                    continue
                if impl == "split":
                    continue  # per-read records emitted by the driver
                # amortized per-read SLO records (same contract as
                # pyapi.msa_batch): the sub-batch wall split evenly across
                # every read it carried
                from ..obs import record_read
                from ..pipeline import _band_cols
                n_sub = sum(len(e[1]) for e in piece)
                share = (time.perf_counter() - t0) / max(1, n_sub)
                for e in piece:
                    for b in e[1]:
                        record_read(share, len(b), _band_cols(abpt, len(b)),
                                    abpt.device, amortized=True)
    for (idx, _seqs, _w, ab), res in zip(flat, outs):
        if res is None:
            continue
        pg, is_rc = res
        ab.graph = pg
        if abpt.amb_strand:
            for j, flag in enumerate(is_rc):
                ab.is_rc[j] = flag
        # reads are fused; output walks only names/quals/graph. Blank the
        # sequence strings so the segment doesn't hold every set's reads
        # in memory at once (n_seq must stay correct).
        ab.seqs = [""] * len(ab.seqs)
        results[idx] = ab
    return results


def flush_lockstep_group_churn(group: List, abpt: Params, devices: List,
                               gi: int, churn, mesh=None) -> None:
    """Continuous-batching variant of flush_lockstep_group (serve-only):
    run one same-rung group of (idx, ab, seqs, weights) entries through
    the SPLIT driver with a round-boundary churn hook. Results are
    delivered exclusively through ``churn.on_retire`` the round each lane
    finishes — there is no result dict, because by the time the call
    returns every lane (initial and joined) has already been answered.

    No length-bucket partition and no memory admission_plan here: the
    serve coalescer already packs a single Qp rung, and the admission byte
    gate priced the group (and prices every joiner against the LIVE group
    via claim_joiners) — a second static plan over the pickup snapshot
    would be wrong the moment a lane retires. Dispatch failures raise
    (DispatchFailed/RuntimeError) for the caller's per-job sweep."""
    if not group:
        return
    from ..obs import count, device_capture, observe, trace
    from .. import resilience as rz
    from .lockstep import progressive_poa_split_batch
    count("lockstep.groups")
    observe("lockstep.group_size", len(group))
    backend = "jax" if abpt.device == "tpu" else abpt.device
    dev = devices[gi % len(devices)] if devices else None
    with trace.span("lockstep_group", "fused",
                    args={"k": len(group), "group": gi, "impl": "split",
                          "churn": True}), \
            device_capture("lockstep_group"):
        with _default_device(dev):
            rz.guarded_device_call(
                "lockstep_batch", backend,
                lambda: progressive_poa_split_batch(
                    [e[2] for e in group], [e[3] for e in group],
                    abpt, churn=churn, mesh=mesh))


def run_batch(files: Sequence[str], abpt: Params, out_fp: IO[str],
              devices: List = None) -> dict:
    """Process independent read-set files (the `-l` mode): lockstep-batched
    on device when eligible (a real accelerator mesh, or explicit opt-in —
    see `lockstep_enabled`), sequential round-robin otherwise. Output
    order and bytes match sequential processing exactly.

    Per-set quarantine: a file that fails to parse/validate produces a
    structured per-set error (a `faults` record + one stderr line) and the
    remaining sets complete — one poisoned set never drops the batch.
    Returns {"sets", "quarantined"} so the CLI can pick its exit status.

    Lockstep processing streams SEGMENT by segment (a segment ends when K
    eligible sets have accumulated): each segment is computed as one
    vmapped dispatch, then emitted in file order, so peak memory is one
    group's read sets + graphs — not the whole file list."""
    from .. import resilience as rz
    from ..obs import metrics as _metrics
    from ..pipeline import Abpoa, msa_from_file, output
    from . import scheduler
    stats = {"sets": len(files), "quarantined": 0}
    if not (abpt.out_msa or abpt.out_cons or abpt.out_gfa):
        return stats  # mirror msa_from_file: nothing to emit or compute
    route = None
    if devices is None:
        # ONE decision site over pool x lockstep x hybrid (scheduler.py);
        # an explicit `devices` list is a test hook that pins the legacy
        # in-process routing
        scheduler.reset()
        route = scheduler.plan_route(abpt, len(files))
        if route.kind == "pool":
            # CPU-default multi-process set pool (--workers N /
            # ABPOA_TPU_WORKERS, auto = one worker per core): also buys
            # crash containment and hard-kill deadlines (pool.py)
            from .pool import run_pool_batch
            return run_pool_batch(files, abpt, out_fp, route.workers)
        if route.kind == "hybrid":
            # pool-of-lockstep-groups: worker processes each running a
            # split-lockstep group of route.k_cap sets
            from .pool import run_hybrid_batch
            return run_hybrid_batch(files, abpt, out_fp, route.workers,
                                    route.k_cap)
    lock = route.kind in ("lockstep", "sharded") if route is not None \
        else _lockstep_ok(abpt)
    mesh = None
    # live batch-progress gauges: `abpoa-tpu top` shows sets done / total
    # while the -l run executes (the exporter flusher publishes them)
    _metrics.publish_batch_progress(0, total=len(files))
    _mark_set_done = _metrics.bump_batch_set_done
    if devices is None:
        if lock or abpt.device in ("jax", "tpu", "pallas"):
            # probe BEFORE jax.devices(): a wedged accelerator tunnel hangs
            # any in-process backend init forever (utils/probe.py); the
            # per-file msa path then falls back to the host engine itself
            from ..utils.probe import (apply_platform_pin,
                                       jax_backend_reachable,
                                       warn_unreachable_once)
            if jax_backend_reachable():
                apply_platform_pin()
                if route is not None and route.kind == "sharded":
                    # mesh discovery BEFORE jax.devices(): the virtual
                    # CPU mesh pin is a no-op once the backend is up
                    from .shard import discover_mesh
                    mesh = discover_mesh(route.workers)
                import jax
                devices = jax.devices()
            else:
                warn_unreachable_once(
                    "Warning: JAX backend probe timed out (wedged "
                    "accelerator tunnel?); falling back to the host engine.")
                lock = False
                devices = [None]
        else:
            devices = [None]

    def run_one(ab, i, fn):
        from ..obs import trace
        abpt.batch_index = i + 1
        dev = devices[i % len(devices)]
        with trace.span(f"set:{i}", "set", args={"file": fn}):
            if dev is None:
                msa_from_file(ab, abpt, fn, out_fp)
            else:
                import jax
                with jax.default_device(dev):
                    msa_from_file(ab, abpt, fn, out_fp)

    def run_one_quarantined(ab, i, fn):
        """Sequential per-file run with the per-set quarantine boundary:
        malformed input / I/O decay isolates THIS set; real bugs still
        propagate (rz.QUARANTINE_EXCEPTIONS is the closed list)."""
        try:
            run_one(ab, i, fn)
        except rz.QUARANTINE_EXCEPTIONS as e:
            rz.quarantine_set(i, fn, e)
            stats["quarantined"] += 1

    if not lock:
        ab = Abpoa()
        for i, fn in enumerate(files):
            run_one_quarantined(ab, i, fn)
            _mark_set_done()
        return stats

    from ..align.eligibility import fused_eligible
    from ..io.fastx import read_fastx
    from ..pipeline import _ingest_records
    base_K = route.k_cap if route is not None else lockstep_group_size()
    K = base_K
    ab_seq = Abpoa()
    seg: List = []    # [(file_idx, fn)] for the current segment
    group: List = []  # [(file_idx, ab, seqs, weights)] eligible subset
    gi = 0

    def emit_segment() -> None:
        nonlocal gi, K
        results = flush_lockstep_group(group, abpt, devices, gi,
                                       impl=route.impl if route else None,
                                       mesh=mesh)
        gi += 1
        # divergence feedback: measured noop_set_fraction re-caps the NEXT
        # segment's group size (scheduler.noop_k_cap) — per route, so the
        # sharded cap reprices the whole mesh from its own EWMA
        if route is not None and route.kind == "sharded":
            K = route.workers * scheduler.noop_k_cap(
                lockstep_group_size(), route="sharded")
        else:
            K = scheduler.noop_k_cap(base_K)
        for idx, fn in seg:
            if idx in results:
                abpt.batch_index = idx + 1
                output(results[idx], abpt, out_fp)
            else:
                # ineligible or device-failed: sequential path (re-reads the
                # file; IO is negligible next to alignment)
                run_one_quarantined(ab_seq, idx, fn)
            _mark_set_done()
        seg.clear()
        group.clear()

    for i, fn in enumerate(files):
        try:
            records = read_fastx(fn)
            rz.validate_records(records, abpt, label=fn)
            ab = Abpoa()
            seqs, weights = _ingest_records(ab, abpt, records)
        except rz.QUARANTINE_EXCEPTIONS as e:
            # per-set quarantine: report this set, keep the batch going
            rz.quarantine_set(i, fn, e)
            stats["quarantined"] += 1
            _mark_set_done()
            continue
        seg.append((i, fn))
        if fused_eligible(abpt, len(seqs)):
            group.append((i, ab, seqs, weights))
        if len(group) == K:
            emit_segment()
    emit_segment()
    return stats


def run_lockstep_files(pairs, abpt: Params) -> dict:
    """One lockstep group over `pairs` = [(file_idx, path), ...], outputs
    captured per file — the hybrid route's unit of work (a pool worker
    executes this for its group job). Ineligible/failed/quarantined sets
    take the per-set sequential path with the usual quarantine boundary.

    Returns {"texts": {idx: str}, "quarantined": [idx, ...]}.
    """
    import io as _io
    from .. import resilience as rz
    from ..align.eligibility import fused_eligible
    from ..io.fastx import read_fastx
    from ..pipeline import Abpoa, _ingest_records, msa_from_file, output
    texts: dict = {}
    quarantined: list = []
    group = []
    for idx, fn in pairs:
        try:
            records = read_fastx(fn)
            rz.validate_records(records, abpt, label=fn)
            ab = Abpoa()
            seqs, weights = _ingest_records(ab, abpt, records)
        except rz.QUARANTINE_EXCEPTIONS as e:
            rz.quarantine_set(idx, fn, e)
            quarantined.append(idx)
            texts[idx] = ""
            continue
        if fused_eligible(abpt, len(seqs)):
            group.append((idx, ab, seqs, weights))
        # ineligible sets take the per-file sequential path below (they
        # are simply absent from `results`)
    results = flush_lockstep_group(group, abpt, None, 0, impl="split")
    for idx, fn in pairs:
        if idx in texts and idx not in results:
            continue  # already quarantined above
        buf = _io.StringIO()
        if idx in results:
            abpt.batch_index = idx + 1
            output(results[idx], abpt, buf)
        else:
            try:
                abpt.batch_index = idx + 1
                msa_from_file(Abpoa(), abpt, fn, buf)
            except rz.QUARANTINE_EXCEPTIONS as e:
                rz.quarantine_set(idx, fn, e)
                quarantined.append(idx)
        texts[idx] = buf.getvalue()
    return {"texts": texts, "quarantined": sorted(set(quarantined))}


def shard_dp_batch(mesh_devices: int = None):
    """Build a sharded batched DP step over an n-device mesh.

    Returns (mesh, step_fn) where step_fn takes per-set stacked kernel inputs
    (leading dim = number of read sets) and runs each set's DP scan on its own
    mesh slot. Used by __graft_entry__.dryrun_multichip and as the scaffold for
    multi-set batch processing.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from ..align.jax_backend import _dp_scan
    from .. import constants as C

    devs = jax.devices()
    n = mesh_devices or len(devs)
    mesh = Mesh(np.array(devs[:n]), axis_names=("set",))

    def one_set(base, pre_idx, pre_msk, out_idx, out_msk, row_active,
                remain_rows, mpl0, mpr0, qp, scalars):
        (qlen, w, remain_end, inf_min, dp_end0,
         o1, e1, oe1, o2, e2, oe2) = [scalars[i] for i in range(11)]
        n_steps = base.shape[0] - 1
        out = _dp_scan(base, pre_idx, pre_msk, out_idx, out_msk, row_active,
                       remain_rows, mpl0, mpr0, qp,
                       qlen, w, remain_end, inf_min, dp_end0,
                       o1, e1, oe1, o2, e2, oe2,
                       gap_mode=C.CONVEX_GAP, local=False, banded=True,
                       n_steps=n_steps)
        return out[0]  # H planes

    specs = tuple(P("set") for _ in range(11))

    from ..utils.jaxcompat import shard_map

    @jax.jit
    def step(*stacked):
        fn = shard_map(jax.vmap(one_set), mesh=mesh, in_specs=specs,
                       out_specs=P("set"))
        return fn(*stacked)

    return mesh, step
