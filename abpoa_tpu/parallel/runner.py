"""Multi-device scaling: shard independent read sets across a TPU mesh.

The POA algorithm needs no cross-chip collectives (SURVEY.md §2.3): the unit of
work "align read set -> call consensus" fits one chip, so fleet scaling is data
parallelism over read sets (the reference's `-l` file-list mode,
/root/reference/src/abpoa.c:148-168). Two layers:

- `run_batch`: round-robin read-set files over local devices; each set's DP
  kernels are placed on its device via `jax.default_device`, host fusion stays
  on CPU threads. No collectives ride the interconnect.
- `shard_dp_batch`: a `shard_map`-over-Mesh batched DP step — many same-bucket
  alignments at once, one per mesh slot. This is the building block for the
  all-device progressive loop (PERF.md) and for multi-host DCN fan-out, where
  each host feeds its local mesh slice.
"""
from __future__ import annotations

from typing import IO, List, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..params import Params


def run_batch(files: Sequence[str], abpt: Params, out_fp: IO[str],
              devices: List = None) -> None:
    """Process independent read-set files, round-robin across devices."""
    from ..pipeline import Abpoa, msa_from_file
    devices = devices or jax.devices()
    ab = Abpoa()
    for i, fn in enumerate(files):
        abpt.batch_index = i + 1
        dev = devices[i % len(devices)]
        with jax.default_device(dev):
            msa_from_file(ab, abpt, fn, out_fp)


def shard_dp_batch(mesh_devices: int = None):
    """Build a sharded batched DP step over an n-device mesh.

    Returns (mesh, step_fn) where step_fn takes per-set stacked kernel inputs
    (leading dim = number of read sets) and runs each set's DP scan on its own
    mesh slot. Used by __graft_entry__.dryrun_multichip and as the scaffold for
    multi-set batch processing.
    """
    from ..align.jax_backend import _dp_scan
    from .. import constants as C

    devs = jax.devices()
    n = mesh_devices or len(devs)
    mesh = Mesh(np.array(devs[:n]), axis_names=("set",))

    def one_set(base, pre_idx, pre_msk, out_idx, out_msk, row_active,
                remain_rows, mpl0, mpr0, qp, scalars):
        (qlen, w, remain_end, inf_min, dp_end0,
         o1, e1, oe1, o2, e2, oe2) = [scalars[i] for i in range(11)]
        n_steps = base.shape[0] - 1
        out = _dp_scan(base, pre_idx, pre_msk, out_idx, out_msk, row_active,
                       remain_rows, mpl0, mpr0, qp,
                       qlen, w, remain_end, inf_min, dp_end0,
                       o1, e1, oe1, o2, e2, oe2,
                       gap_mode=C.CONVEX_GAP, local=False, banded=True,
                       n_steps=n_steps)
        return out[0]  # H planes

    specs = tuple(P("set") for _ in range(11))

    @jax.jit
    def step(*stacked):
        fn = jax.shard_map(jax.vmap(one_set), mesh=mesh, in_specs=specs,
                           out_specs=P("set"), check_vma=False)
        return fn(*stacked)

    return mesh, step
