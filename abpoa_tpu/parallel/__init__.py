from .runner import (flush_lockstep_group, lockstep_enabled,
                     lockstep_group_size, run_batch, shard_dp_batch)
