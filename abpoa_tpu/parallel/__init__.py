from .pool import (PoolJob, PoolWorkerError, WorkerPool, resolve_workers,
                   run_hybrid_batch, run_pool_batch)
from .runner import (flush_lockstep_group, flush_lockstep_group_churn,
                     lockstep_enabled, lockstep_group_size, run_batch,
                     run_lockstep_files, shard_dp_batch)
from .map_driver import (MapHook, load_static_graph, map_read_host,
                         map_reads_split)
from .scheduler import Route, plan_route
from .shard import (discover_mesh, mesh_size, pin_virtual_cpu_mesh,
                    requested_mesh_size, shard_dp_round, shard_vmap)
