from .runner import lockstep_enabled, run_batch, shard_dp_batch
