from .pool import (PoolJob, PoolWorkerError, WorkerPool, resolve_workers,
                   run_pool_batch)
from .runner import (flush_lockstep_group, lockstep_enabled,
                     lockstep_group_size, run_batch, shard_dp_batch)
