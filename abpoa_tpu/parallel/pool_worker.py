"""Pool worker entry point: ``python -m abpoa_tpu.parallel.pool_worker``.

One long-lived worker process of the supervised pool (parallel/pool.py).
Length-prefixed pickle frames over stdin/stdout:

    parent -> worker   {"params", "label"}                      (init, once)
    worker -> parent   ("ready", pid)
    parent -> worker   ("job", id, kind, payload, spec, kill, meta)  per job
                       meta = {"rid", "attempt", "trace", "label"} — the
                       request context (PR 15): trace ids cross the pipe
    worker -> parent   ("hb", id, rss_bytes)                    while running
    worker -> parent   ("ok", id, result) | ("err", id, message)
    parent -> worker   None                                     (shutdown)

The real stdout fd is reserved for the protocol: it is dup'd away at
startup and fd 1 is pointed at stderr, so a stray library print (or an
XLA banner) can never corrupt a frame. The heartbeat thread and the
result path share one write lock — frames are atomic on the pipe.
"""
from __future__ import annotations

import os
import sys
import threading


def main() -> int:
    # keep the protocol pipe, route any other fd-1 writer to stderr
    proto_out = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    sys.stdout = os.fdopen(1, "w")
    inp = sys.stdin.buffer

    from abpoa_tpu.parallel import pool as P
    init = P.read_frame(inp)
    P.worker_init(init)
    wlock = threading.Lock()
    with wlock:
        P.write_frame(proto_out, ("ready", os.getpid()))
    from abpoa_tpu.obs import flight
    while True:
        try:
            msg = P.read_frame(inp)
        except EOFError:
            flight.shutdown()   # clean exit: nothing died, no dump kept
            return 0
        if msg is None:
            flight.shutdown()
            return 0
        _tag, job_id, kind, payload, spec, kill_kind, meta = msg
        stop = threading.Event()
        hb = threading.Thread(target=P.heartbeat_loop,
                              args=(proto_out, wlock, job_id, stop),
                              daemon=True, name="abpoa-pool-heartbeat")
        hb.start()
        try:
            frame = P.worker_run_job(job_id, kind, payload, spec, kill_kind,
                                     meta)
        except Exception as e:  # noqa: BLE001 — serialized for the parent,
            # which re-raises it as PoolWorkerError (real bugs propagate)
            import traceback
            frame = ("err", job_id,
                     f"{type(e).__name__}: {e}\n"
                     f"{traceback.format_exc(limit=20)}")
        finally:
            stop.set()
            hb.join(timeout=2.0)
        with wlock:
            P.write_frame(proto_out, frame)


if __name__ == "__main__":
    raise SystemExit(main())
