"""ONE scheduler over pool x lockstep x hybrid.

Before this module the batch route was ad-hoc: `align/dispatch.py` picked
kernels, `parallel/runner.py` picked pool-vs-lockstep inline, and
`serve/server.py` re-derived coalescing eligibility itself. Every consumer
(the `-l` runner, the serve coalescer, the bench harness) now asks ONE
decision site, and the decision is recorded (report counters
`scheduler.<route>` -> Prometheus `abpoa_scheduler_routes_total{route=}`,
plus a `last route` gauge panel in `abpoa-tpu top`).

Routes:

- **serial**    one set at a time through the single-set engine
- **pool**      supervised worker processes, one set per job (CPU
                multicore default — PR 13)
- **lockstep**  in-process K-set groups; impl "device" = the all-device
                vmapped fused loop (real accelerator mesh: scatters lower
                to DMA, the set axis shards 1:1), impl "split" = host
                fusion + batched banded-DP rounds (parallel/lockstep.py —
                CPU hosts, where vmapped fusion scatters measured 1.37x
                slower than serial, ROUND8_NOTES.md)
- **hybrid**    pool-of-lockstep-groups: worker processes each running a
                split-lockstep group (explicit --workers N on a multicore
                host with more sets than one group holds)

The lockstep K cap is fed back from measured divergence: every split
round reports its idle-lane fraction (`lockstep.noop_set_fraction`), an
EWMA of which halves the next groups' K per 0.25 of no-op (divergent-
length sets stop paying for each other's drain).
"""
from __future__ import annotations

import os
from typing import NamedTuple, Optional


class Route(NamedTuple):
    kind: str       # "serial" | "pool" | "lockstep" | "hybrid" | "map"
    impl: str       # lockstep implementation: "split" | "device" | ""
    k_cap: int      # sets per lockstep group (lockstep/hybrid/map)
    workers: int    # worker processes (pool/hybrid)
    reason: str


# EWMA of the measured idle-lane fraction across lockstep rounds/groups of
# this run (reset per batch); drives the sub-batch K cap
_NOOP = {"ewma": 0.0, "seen": False}
NOOP_HALVING_STEP = 0.25

# EWMA of measured lane occupancy (live lanes / group capacity) fed per
# round by the split driver's lane table; under churn this is the direct
# gauge of how full the dispatched rung actually is (joins backfill retired
# lanes, so it stays near 1.0 instead of decaying with the drain)
_OCC = {"ewma": 1.0, "seen": False, "sum": 0.0, "n": 0}

# Below this query length serial wins over lockstep on CPU hosts: the
# per-round host fusion + dispatch overhead isn't amortized by the tiny DP
# plane (the ~1.5 kb crossover measured in PERF.md round 14 / the
# lockstep_gate sim sets). plan_route(qlen=...) routes below it to serial.
LOCKSTEP_MIN_QLEN = 1500


def reset() -> None:
    _NOOP["ewma"] = 0.0
    _NOOP["seen"] = False
    _OCC["ewma"] = 1.0
    _OCC["seen"] = False
    _OCC["sum"] = 0.0
    _OCC["n"] = 0


def observe_noop_fraction(f: float) -> None:
    """Fed by the lockstep drivers each round/group; mirrored to the
    `abpoa_lockstep_noop_fraction` gauge so `top` can watch the K-cap
    heuristic's input live."""
    f = min(max(float(f), 0.0), 1.0)
    _NOOP["ewma"] = f if not _NOOP["seen"] else (
        0.5 * _NOOP["ewma"] + 0.5 * f)
    _NOOP["seen"] = True
    from ..obs import metrics
    metrics.publish_noop_fraction(_NOOP["ewma"])


def noop_ewma() -> float:
    return _NOOP["ewma"]


def observe_lane_occupancy(occ: float) -> None:
    """Fed by the split driver's lane table once per round: live lanes over
    group capacity. Publishes the `abpoa_lockstep_lane_occupancy` gauge and
    feeds the same K-cap EWMA as `observe_noop_fraction` (noop = 1 - occ),
    so the cap reacts to measured occupancy whether or not churn is on."""
    occ = min(max(float(occ), 0.0), 1.0)
    _OCC["ewma"] = occ if not _OCC["seen"] else (
        0.5 * _OCC["ewma"] + 0.5 * occ)
    _OCC["seen"] = True
    _OCC["sum"] += occ
    _OCC["n"] += 1
    from ..obs import metrics
    metrics.publish_lane_occupancy(_OCC["ewma"])
    observe_noop_fraction(1.0 - occ)


def occupancy_ewma() -> float:
    return _OCC["ewma"]


def occupancy_mean() -> float:
    """Unweighted mean of every per-round occupancy observation since
    reset(). The EWMA's 0.5 blend makes it a recency gauge — it tracks the
    tail of a run, which under churn is always the drain of the last open
    group (no more joiners to backfill). For whole-run comparisons (the
    churn gate's A/B) the mean is the honest estimator."""
    return _OCC["sum"] / _OCC["n"] if _OCC["n"] else 1.0


def lockstep_min_qlen() -> int:
    """Serial-vs-lockstep crossover in query bp; ABPOA_TPU_LOCKSTEP_MIN_QLEN
    overrides (0 disables the qlen gate entirely)."""
    try:
        return int(os.environ.get("ABPOA_TPU_LOCKSTEP_MIN_QLEN",
                                  str(LOCKSTEP_MIN_QLEN)))
    except ValueError:
        return LOCKSTEP_MIN_QLEN


def noop_k_cap(base_k: int, noop: Optional[float] = None) -> int:
    """Sub-batch K cap from measured divergence: each NOOP_HALVING_STEP
    (0.25) of idle-lane fraction halves the group, floor 1. At 0.5 noop a
    K=8 group becomes K=2: sets mostly draining alone stop occupying (and
    waiting on) a wide batch."""
    f = _NOOP["ewma"] if noop is None else noop
    k = max(1, int(base_k))
    while f >= NOOP_HALVING_STEP and k > 1:
        k //= 2
        f -= NOOP_HALVING_STEP
    return k


def _explicit_workers(abpt) -> int:
    """Operator-requested worker count (pool.explicit_workers — ONE
    grammar for the --workers/env knob), 0 if unset. Hybrid requires the
    explicit opt-in for the same reason pool auto never forks
    device-family backends: N workers = N accelerator clients."""
    from .pool import explicit_workers
    return explicit_workers(abpt)


def lockstep_impl(abpt) -> str:
    """Which lockstep implementation fits this host: the all-device vmapped
    fused loop needs real accelerator silicon (scatters lower to DMA, the
    set axis shards across chips); on CPU hosts the split driver wins
    (ROUND8_NOTES.md / PERF.md round 14). ABPOA_TPU_LOCKSTEP_IMPL
    overrides for measurement."""
    forced = os.environ.get("ABPOA_TPU_LOCKSTEP_IMPL", "").strip().lower()
    if forced in ("split", "device"):
        return forced
    from ..utils.probe import has_accelerator
    return "device" if has_accelerator() else "split"


def plan_route(abpt, n_sets: int, serve: bool = False,
               qlen: Optional[int] = None,
               workload: str = "consensus") -> Route:
    """THE batch/serve dispatch decision: device inventory (accelerator vs
    CPU, core count via pool.resolve_workers), lockstep eligibility
    (config scope + opt-in), and the noop-fraction K cap, in one place.

    serve=True plans the coalescing path: pool-vs-serial is the server's
    own --pool-workers knob, so only serial/lockstep come back.

    qlen, when known, is the batch's max query length: below the measured
    ~1.5 kb crossover (lockstep_min_qlen) the per-round fusion + dispatch
    overhead loses to serial even with lockstep enabled, so such sets
    route serial/pool rather than occupying a lockstep group.

    workload="map" plans the fixed-graph map route instead: there is no
    per-round host fusion to amortize, so neither the 1.5 kb qlen
    crossover nor `_lockstep_ok`'s no-incremental-graph clause applies
    (map BY DEFINITION restores via abpt.incr_fn). The K cap still rides
    the measured-occupancy feedback.
    """
    from .runner import _lockstep_ok, lockstep_group_size
    if workload == "map":
        route = _plan_map(abpt, n_sets, lockstep_group_size)
    else:
        route = _plan(abpt, n_sets, serve, _lockstep_ok,
                      lockstep_group_size, qlen)
    from ..obs import count, metrics, trace
    count(f"scheduler.{route.kind}")
    metrics.publish_route(route)
    # route decisions land on the trace timeline too: a request whose
    # group ran serial-fallback (or K-capped) can show why in its tree
    trace.instant("route", "sched", args=route._asdict())
    return route


def _plan_map(abpt, n_reads, lockstep_group_size) -> Route:
    """The map workload's route: batched split-DP rounds whenever a
    jax-family backend is present (the map driver IS the split dispatch
    minus fusion), serial per-read host alignment otherwise. No qlen
    crossover — a short read costs one round like a long one."""
    if n_reads <= 0:
        return Route("serial", "", 1, 1, "empty read stream")
    if abpt.device not in ("jax", "tpu", "pallas"):
        return Route("serial", "", 1, 1,
                     f"device {abpt.device!r} has no batched DP chunk")
    base_k = lockstep_group_size()
    k_cap = noop_k_cap(base_k)
    reason = f"map split k_cap={k_cap}"
    if k_cap != base_k:
        reason += f" (noop ewma {_NOOP['ewma']:.2f} capped {base_k})"
    return Route("map", "split", k_cap, 1, reason)


def _plan(abpt, n_sets, serve, _lockstep_ok, lockstep_group_size,
          qlen=None) -> Route:
    if n_sets <= 0:
        return Route("serial", "", 1, 1, "empty batch")
    min_q = lockstep_min_qlen()
    below_crossover = qlen is not None and qlen < min_q
    if not _lockstep_ok(abpt) or below_crossover:
        why = (f"qlen {qlen} < serial-wins crossover {min_q}"
               if below_crossover else "lockstep ineligible")
        if serve:
            return Route("serial", "", 1, 1, why)
        from .pool import resolve_workers
        w = resolve_workers(abpt, n_sets)
        if w > 1 and n_sets > 1:
            return Route("pool", "", 1, w,
                         f"{w} workers over {n_sets} sets (CPU multicore)"
                         + (f"; {why}" if below_crossover else ""))
        return Route("serial", "", 1, 1,
                     why if below_crossover
                     else "single set/core, or lockstep ineligible")
    impl = lockstep_impl(abpt)
    base_k = lockstep_group_size()
    k_cap = noop_k_cap(base_k)
    reason = f"impl={impl} k_cap={k_cap}"
    if k_cap != base_k:
        reason += f" (noop ewma {_NOOP['ewma']:.2f} capped {base_k})"
    if not serve and impl == "split":
        w = _explicit_workers(abpt)
        if w > 1 and n_sets > k_cap:
            groups = -(-n_sets // k_cap)
            return Route("hybrid", impl, k_cap, min(w, groups),
                         reason + f" x {min(w, groups)} group workers")
    return Route("lockstep", impl, k_cap, 1, reason)
