"""ONE scheduler over pool x lockstep x hybrid x sharded.

Before this module the batch route was ad-hoc: `align/dispatch.py` picked
kernels, `parallel/runner.py` picked pool-vs-lockstep inline, and
`serve/server.py` re-derived coalescing eligibility itself. Every consumer
(the `-l` runner, the serve coalescer, the bench harness) now asks ONE
decision site, and the decision is recorded (report counters
`scheduler.<route>` -> Prometheus `abpoa_scheduler_routes_total{route=}`,
plus a `last route` gauge panel in `abpoa-tpu top`).

Routes:

- **serial**    one set at a time through the single-set engine
- **pool**      supervised worker processes, one set per job (CPU
                multicore default — PR 13)
- **lockstep**  in-process K-set groups; impl "device" = the all-device
                vmapped fused loop (real accelerator mesh: scatters lower
                to DMA, the set axis shards 1:1), impl "split" = host
                fusion + batched banded-DP rounds (parallel/lockstep.py —
                CPU hosts, where vmapped fusion scatters measured 1.37x
                slower than serial, ROUND8_NOTES.md)
- **hybrid**    pool-of-lockstep-groups: worker processes each running a
                split-lockstep group (explicit --workers N on a multicore
                host with more sets than one group holds)
- **sharded**   the split driver's one-dispatch-per-round batch spread
                over an explicit device mesh (--mesh N / ABPOA_TPU_MESH,
                parallel/shard.py): impl "split" = consensus lockstep,
                impl "map" = the fixed-graph map stream. K cap = mesh
                size x the per-chip noop-capped group size, so one chip's
                worth of divergence feedback scales the whole mesh.

The lockstep K cap is fed back from measured divergence: every split
round reports its idle-lane fraction (`lockstep.noop_set_fraction`), an
EWMA of which halves the next groups' K per 0.25 of no-op (divergent-
length sets stop paying for each other's drain). The EWMAs are PER ROUTE
(lockstep / map / sharded): the map stream's zero-barrier occupancy sits
near 1.0 by construction and must not launder the consensus path's
drain-tail divergence out of its K cap (nor vice versa).
"""
from __future__ import annotations

import os
from typing import NamedTuple, Optional


class Route(NamedTuple):
    kind: str       # "serial" | "pool" | "lockstep" | "hybrid" | "map"
                    # | "sharded"
    impl: str       # lockstep implementation: "split" | "device" | "map"
                    # | "" (sharded reuses it for the workload flavour)
    k_cap: int      # sets per lockstep group (sharded: GLOBAL lanes =
                    # mesh x per-chip cap)
    workers: int    # worker processes (pool/hybrid); mesh size (sharded)
    reason: str
    code: str = "unspecified"
                    # categorical decision code behind `reason`'s free
                    # text — the `reason` label on
                    # abpoa_scheduler_routes_total, so the perf ledger's
                    # route mix can tell crossover-serial ("crossover")
                    # from ineligible/explicit-serial ("ineligible"),
                    # multicore pool ("multicore"), an eligible lockstep/
                    # map grant ("eligible"), a mesh upgrade ("mesh"),
                    # hybrid group workers ("workers"), or an empty batch
                    # ("empty")


# measured-feedback state, PER ROUTE: the idle-lane (noop) EWMA that caps
# K, and the lane-occupancy estimators the gates compare. Keyed by the
# observing route so one workload's occupancy cannot inflate (or starve)
# another's K-cap feedback — the map stream idles ~never while the
# consensus drain tail idles plenty, and each must see only its own.
ROUTES = ("lockstep", "map", "sharded")
NOOP_HALVING_STEP = 0.25


def _new_noop() -> dict:
    return {"ewma": 0.0, "seen": False}


def _new_occ() -> dict:
    return {"ewma": 1.0, "seen": False, "sum": 0.0, "n": 0}


_NOOP = {r: _new_noop() for r in ROUTES}
_OCC = {r: _new_occ() for r in ROUTES}

# Below this query length serial wins over lockstep on CPU hosts: the
# per-round host fusion + dispatch overhead isn't amortized by the tiny DP
# plane (the ~1.5 kb crossover measured in PERF.md round 14 / the
# lockstep_gate sim sets). plan_route(qlen=...) routes below it to serial.
LOCKSTEP_MIN_QLEN = 1500


def reset() -> None:
    for r in ROUTES:
        _NOOP[r] = _new_noop()
        _OCC[r] = _new_occ()


def observe_noop_fraction(f: float, route: str = "lockstep") -> None:
    """Fed by the lockstep drivers each round/group; mirrored to the
    `abpoa_lockstep_noop_fraction` gauge so `top` can watch the K-cap
    heuristic's input live. `route` keys the EWMA: each route's cap reacts
    only to its own measured divergence."""
    f = min(max(float(f), 0.0), 1.0)
    st = _NOOP[route]
    st["ewma"] = f if not st["seen"] else (0.5 * st["ewma"] + 0.5 * f)
    st["seen"] = True
    from ..obs import metrics
    metrics.publish_noop_fraction(st["ewma"])


def noop_ewma(route: str = "lockstep") -> float:
    return _NOOP[route]["ewma"]


def observe_lane_occupancy(occ: float, route: str = "lockstep") -> None:
    """Fed by the split driver's lane table once per round: live lanes over
    group capacity. Publishes the `abpoa_lockstep_lane_occupancy` gauge and
    feeds the same K-cap EWMA as `observe_noop_fraction` (noop = 1 - occ),
    so the cap reacts to measured occupancy whether or not churn is on —
    per `route`, so the map stream's by-construction 1.0 occupancy no
    longer launders the consensus drain out of the lockstep cap."""
    occ = min(max(float(occ), 0.0), 1.0)
    st = _OCC[route]
    st["ewma"] = occ if not st["seen"] else (0.5 * st["ewma"] + 0.5 * occ)
    st["seen"] = True
    st["sum"] += occ
    st["n"] += 1
    from ..obs import metrics
    metrics.publish_lane_occupancy(st["ewma"])
    observe_noop_fraction(1.0 - occ, route=route)


def occupancy_ewma(route: str = "lockstep") -> float:
    return _OCC[route]["ewma"]


def occupancy_mean(route: Optional[str] = None) -> float:
    """Unweighted mean of every per-round occupancy observation since
    reset(). The EWMA's 0.5 blend makes it a recency gauge — it tracks the
    tail of a run, which under churn is always the drain of the last open
    group (no more joiners to backfill). For whole-run comparisons (the
    churn gate's A/B) the mean is the honest estimator. `route=None`
    pools every route's observations (the gates' single-workload runs see
    exactly their own route either way)."""
    if route is None:
        total = sum(_OCC[r]["sum"] for r in ROUTES)
        n = sum(_OCC[r]["n"] for r in ROUTES)
        return total / n if n else 1.0
    st = _OCC[route]
    return st["sum"] / st["n"] if st["n"] else 1.0


def lockstep_min_qlen() -> int:
    """Serial-vs-lockstep crossover in query bp; ABPOA_TPU_LOCKSTEP_MIN_QLEN
    overrides (0 disables the qlen gate entirely)."""
    try:
        return int(os.environ.get("ABPOA_TPU_LOCKSTEP_MIN_QLEN",
                                  str(LOCKSTEP_MIN_QLEN)))
    except ValueError:
        return LOCKSTEP_MIN_QLEN


def noop_k_cap(base_k: int, noop: Optional[float] = None,
               route: str = "lockstep") -> int:
    """Sub-batch K cap from measured divergence: each NOOP_HALVING_STEP
    (0.25) of idle-lane fraction halves the group, floor 1. At 0.5 noop a
    K=8 group becomes K=2: sets mostly draining alone stop occupying (and
    waiting on) a wide batch. The feedback is read from `route`'s own
    EWMA (per-route state — the small-fix regression test pins the
    isolation)."""
    f = _NOOP[route]["ewma"] if noop is None else noop
    k = max(1, int(base_k))
    while f >= NOOP_HALVING_STEP and k > 1:
        k //= 2
        f -= NOOP_HALVING_STEP
    return k


def _explicit_workers(abpt) -> int:
    """Operator-requested worker count (pool.explicit_workers — ONE
    grammar for the --workers/env knob), 0 if unset. Hybrid requires the
    explicit opt-in for the same reason pool auto never forks
    device-family backends: N workers = N accelerator clients."""
    from .pool import explicit_workers
    return explicit_workers(abpt)


def lockstep_impl(abpt) -> str:
    """Which lockstep implementation fits this host: the all-device vmapped
    fused loop needs real accelerator silicon (scatters lower to DMA, the
    set axis shards across chips); on CPU hosts the split driver wins
    (ROUND8_NOTES.md / PERF.md round 14). ABPOA_TPU_LOCKSTEP_IMPL
    overrides for measurement."""
    forced = os.environ.get("ABPOA_TPU_LOCKSTEP_IMPL", "").strip().lower()
    if forced in ("split", "device"):
        return forced
    from ..utils.probe import has_accelerator
    return "device" if has_accelerator() else "split"


def plan_route(abpt, n_sets: int, serve: bool = False,
               qlen: Optional[int] = None,
               workload: str = "consensus",
               mesh: Optional[int] = None) -> Route:
    """THE batch/serve dispatch decision: device inventory (accelerator vs
    CPU, core count via pool.resolve_workers), lockstep eligibility
    (config scope + opt-in), and the noop-fraction K cap, in one place.

    serve=True plans the coalescing path: pool-vs-serial is the server's
    own --pool-workers knob, so only serial/lockstep come back.

    qlen, when known, is the batch's max query length: below the measured
    ~1.5 kb crossover (lockstep_min_qlen) the per-round fusion + dispatch
    overhead loses to serial even with lockstep enabled, so such sets
    route serial/pool rather than occupying a lockstep group.

    workload="map" plans the fixed-graph map route instead: there is no
    per-round host fusion to amortize, so neither the 1.5 kb qlen
    crossover nor `_lockstep_ok`'s no-incremental-graph clause applies
    (map BY DEFINITION restores via abpt.incr_fn). The K cap still rides
    the measured-occupancy feedback.

    mesh, when >= 2 (default: the ABPOA_TPU_MESH/--mesh opt-in via
    shard.requested_mesh_size), upgrades an eligible split-lockstep or
    map plan to the `sharded` route: the SAME one-dispatch-per-round
    driver over a device mesh, K cap = mesh x the per-chip noop cap.
    """
    from .runner import _lockstep_ok, lockstep_group_size
    from .shard import requested_mesh_size
    mesh_n = requested_mesh_size() if mesh is None else max(0, int(mesh))
    if workload == "map":
        route = _plan_map(abpt, n_sets, lockstep_group_size, mesh_n)
    else:
        route = _plan(abpt, n_sets, serve, _lockstep_ok,
                      lockstep_group_size, qlen, mesh_n)
    from ..obs import count, metrics, trace
    count(f"scheduler.{route.kind}.{route.code}")
    metrics.publish_route(route)
    # route decisions land on the trace timeline too: a request whose
    # group ran serial-fallback (or K-capped) can show why in its tree
    trace.instant("route", "sched", args=route._asdict())
    return route


def _plan_map(abpt, n_reads, lockstep_group_size, mesh_n: int = 0) -> Route:
    """The map workload's route: batched split-DP rounds whenever a
    jax-family backend is present (the map driver IS the split dispatch
    minus fusion), serial per-read host alignment otherwise. No qlen
    crossover — a short read costs one round like a long one. A >= 2
    mesh request shards the SAME rounds (kind "sharded", impl "map")."""
    if n_reads <= 0:
        return Route("serial", "", 1, 1, "empty read stream", "empty")
    if abpt.device not in ("jax", "tpu", "pallas"):
        return Route("serial", "", 1, 1,
                     f"device {abpt.device!r} has no batched DP chunk",
                     "ineligible")
    base_k = lockstep_group_size()
    if mesh_n >= 2:
        per_chip = noop_k_cap(base_k, route="sharded")
        return Route("sharded", "map", mesh_n * per_chip, mesh_n,
                     f"sharded map K={mesh_n * per_chip} over mesh={mesh_n}"
                     f" ({mesh_n} x per-chip k_cap {per_chip})", "mesh")
    k_cap = noop_k_cap(base_k, route="map")
    reason = f"map split k_cap={k_cap}"
    if k_cap != base_k:
        reason += (f" (noop ewma {_NOOP['map']['ewma']:.2f} "
                   f"capped {base_k})")
    return Route("map", "split", k_cap, 1, reason, "eligible")


def _plan(abpt, n_sets, serve, _lockstep_ok, lockstep_group_size,
          qlen=None, mesh_n: int = 0) -> Route:
    if n_sets <= 0:
        return Route("serial", "", 1, 1, "empty batch", "empty")
    min_q = lockstep_min_qlen()
    below_crossover = qlen is not None and qlen < min_q
    if not _lockstep_ok(abpt) or below_crossover:
        code = "crossover" if below_crossover else "ineligible"
        why = (f"qlen {qlen} < serial-wins crossover {min_q}"
               if below_crossover else "lockstep ineligible")
        if serve:
            return Route("serial", "", 1, 1, why, code)
        from .pool import resolve_workers
        w = resolve_workers(abpt, n_sets)
        if w > 1 and n_sets > 1:
            return Route("pool", "", 1, w,
                         f"{w} workers over {n_sets} sets (CPU multicore)"
                         + (f"; {why}" if below_crossover else ""),
                         "multicore")
        return Route("serial", "", 1, 1,
                     why if below_crossover
                     else "single set/core, or lockstep ineligible", code)
    impl = lockstep_impl(abpt)
    base_k = lockstep_group_size()
    if mesh_n >= 2 and impl == "split":
        # the sharded route IS the split driver over a mesh; the
        # all-device impl already spans the attached mesh natively, so
        # only split plans upgrade. The global K cap prices the whole
        # mesh from one chip's divergence feedback.
        per_chip = noop_k_cap(base_k, route="sharded")
        return Route("sharded", "split", mesh_n * per_chip, mesh_n,
                     f"sharded K={mesh_n * per_chip} over mesh={mesh_n} "
                     f"({mesh_n} x per-chip k_cap {per_chip})", "mesh")
    k_cap = noop_k_cap(base_k)
    reason = f"impl={impl} k_cap={k_cap}"
    if k_cap != base_k:
        reason += (f" (noop ewma {_NOOP['lockstep']['ewma']:.2f} "
                   f"capped {base_k})")
    if not serve and impl == "split":
        w = _explicit_workers(abpt)
        if w > 1 and n_sets > k_cap:
            groups = -(-n_sets // k_cap)
            return Route("hybrid", impl, k_cap, min(w, groups),
                         reason + f" x {min(w, groups)} group workers",
                         "workers")
    return Route("lockstep", impl, k_cap, 1, reason, "eligible")
