"""Split lockstep: K read sets advance one read per round — host fusion
interleaved with ONE batched device DP dispatch per round.

This is ROUND8_NOTES.md's rewrite #2 ("split fusion out of the vmapped
region entirely"): the all-device lockstep (fused_loop.
progressive_poa_fused_batch) pays the vmapped fusion scatters and the
vmapped while_loop's full-plane selects on every read — measured 1.37x
SLOWER than serial at K=4 on CPU hosts. Here each set's graph lives on the
HOST (the reference add_alignment fusion, byte-golden engine), and only the
banded DP scan + backtrack carry the K axis (align/dp_chunk.run_dp_chunk).
Divergence between sets is visible, not hidden: finished sets free their
lane at pow2 repack boundaries and `lockstep.noop_set_fraction` records the
idle-lane fraction each round — the scheduler's K-cap feedback signal.

Byte parity: per read this is exactly pipeline.poa's sequence (DP at the
pre-fusion graph, optional ambiguous-strand RC retry with the host float
threshold, host add_alignment fusion), so outputs are byte-identical to
the sequential host loop for any K and any set mix.
"""
from __future__ import annotations

import time
from typing import List, Tuple

import numpy as np

from ..params import Params

MAX_W_GROWTH = 6


def progressive_poa_split_batch(seq_sets: List[List[np.ndarray]],
                                weight_sets: List[List[np.ndarray]],
                                abpt: Params) -> list:
    """Run K independent read sets in split lockstep.

    Returns one entry per set: `(host_graph, is_rc_flags)`, or `None` where
    that set must re-run on the caller's sequential path (device backtrack
    divergence) — the same contract as progressive_poa_fused_batch, so the
    two lockstep implementations are drop-in interchangeable at the
    flush_lockstep_group call site.
    """
    from .. import obs
    from ..align.dp_chunk import (build_lockstep_tables, chunk_plane16,
                                  dispatch_dp_chunk, plan_degree_rung,
                                  plan_row_rung, result_from_chunk)
    from ..compile.ladder import k_rung, plan_chunk_buckets, qp_rung
    from ..graph import POAGraph
    from ..pipeline import _band_cols, _rc_encode
    from . import scheduler

    K = len(seq_sets)
    n_reads = [len(ss) for ss in seq_sets]
    qmax = max((len(s) for ss in seq_sets for s in ss), default=1)
    Qp = qp_rung(qmax)
    _qp, W, _local = plan_chunk_buckets(abpt, qmax)
    graphs = [POAGraph() for _ in range(K)]
    is_rc = [[False] * n for n in n_reads]
    cursor = [0] * K
    failed = [False] * K
    amb = bool(abpt.amb_strand)
    obs.observe("lockstep.k", K)

    def fuse_read(k: int, res, qseq, weight) -> None:
        g = graphs[k]
        rid = cursor[k]
        g.add_alignment(abpt, qseq, weight, None, res.cigar, rid,
                        n_reads[k], True)
        cursor[k] += 1

    round_i = 0
    while True:
        active = [k for k in range(K)
                  if not failed[k] and cursor[k] < n_reads[k]]
        if not active:
            break
        t_round = time.perf_counter()
        round_i += 1
        obs.count("lockstep.chunks")
        # idle-lane fraction: real sets already finished (or failed) out of
        # K — the divergence signal the scheduler's K cap feeds on
        noop = 1.0 - len(active) / K
        obs.observe("lockstep.noop_set_fraction", noop)
        scheduler.observe_noop_fraction(noop)
        if noop:
            obs.count("lockstep.drain_chunks")

        # first read of a set seeds its graph: fusion only, no DP
        from ..align.result import AlignResult
        dp_ks = []
        done_this_round: List[Tuple[int, int]] = []  # (set, qlen) advanced
        for k in active:
            if graphs[k].node_n <= 2:
                with obs.phase("fusion"):
                    done_this_round.append((k, len(seq_sets[k][cursor[k]])))
                    fuse_read(k, AlignResult(), seq_sets[k][cursor[k]],
                              weight_sets[k][cursor[k]])
            else:
                dp_ks.append(k)
        if not dp_ks:
            _record_round(abpt, done_this_round, t_round)
            continue

        with obs.phase("align"):
            tables = []
            for k in dp_ks:
                q = seq_sets[k][cursor[k]]
                obs.record_dp(graphs[k].node_n, _band_cols(abpt, len(q)),
                              abpt.gap_mode)
                tables.append(build_lockstep_tables(graphs[k], abpt, q, Qp))
            R = plan_row_rung(max(t["n_rows"] for t in tables))
            P = plan_degree_rung(max(t["pre_idx"].shape[1] for t in tables))
            Kb = k_rung(len(dp_ks))
            plane16 = chunk_plane16(
                abpt, qmax, max(t["n_rows"] for t in tables))
            # the W-growth retry wraps BOTH dispatches: a band overflow on
            # either strand (result_from_chunk returns an empty cigar for
            # it) regrows W and replays the round — an overflowed result
            # must never reach fusion
            for _g in range(MAX_W_GROWTH + 1):
                packed = dispatch_dp_chunk(abpt, tables, Kb, R, P, Qp, W,
                                           plane16)
                results = [result_from_chunk(
                    abpt, packed[i], tables[i],
                    graphs[k].index_to_node_id) for i, k in
                    enumerate(dp_ks)]
                overflowed = any(f["overflow"] for _res, f in results)
                if amb and not overflowed:
                    # ambiguous-strand rescue, host threshold exactly as
                    # pipeline.poa: a sub-threshold forward score retries
                    # the reverse complement against the SAME tables (the
                    # graph is untouched until fusion) in one extra
                    # batched dispatch
                    rc_ks = []
                    for i, k in enumerate(dp_ks):
                        res, _f = results[i]
                        q = seq_sets[k][cursor[k]]
                        thr = (min(len(q), graphs[k].node_n - 2)
                               * abpt.max_mat * 0.3333)
                        if res.best_score < thr:
                            rc_ks.append(i)
                    if rc_ks:
                        rc_tables = []
                        for i in rc_ks:
                            k = dp_ks[i]
                            q = seq_sets[k][cursor[k]]
                            rc_q = _rc_encode(q)
                            obs.record_dp(graphs[k].node_n,
                                          _band_cols(abpt, len(rc_q)),
                                          abpt.gap_mode)
                            t = dict(tables[i])
                            qp = np.zeros_like(t["qp"])
                            query_pad = np.zeros_like(t["query"])
                            if len(rc_q):
                                qp[:, 1: len(rc_q) + 1] = abpt.mat[:, rc_q]
                                query_pad[:len(rc_q)] = rc_q
                            t["qp"] = qp
                            t["query"] = query_pad
                            rc_tables.append(t)
                        rc_packed = dispatch_dp_chunk(abpt, rc_tables, Kb,
                                                      R, P, Qp, W, plane16)
                        for j, i in enumerate(rc_ks):
                            k = dp_ks[i]
                            rc_res, rc_f = result_from_chunk(
                                abpt, rc_packed[j], rc_tables[j],
                                graphs[k].index_to_node_id)
                            if rc_f["overflow"]:
                                overflowed = True
                            elif rc_f["bt_err"]:
                                results[i] = (results[i][0],
                                              {"overflow": False,
                                               "bt_err": True})
                            elif (rc_res.best_score
                                  > results[i][0].best_score):
                                results[i] = (rc_res,
                                              {"overflow": False,
                                               "bt_err": False,
                                               "rc": True})
                if not overflowed:
                    break
                W *= 2
                obs.count("fused.grow.band")
            else:
                raise RuntimeError(
                    "split lockstep: band growth did not converge")

        with obs.phase("fusion"):
            for i, k in enumerate(dp_ks):
                res, f = results[i]
                if f["bt_err"]:
                    # device backtrack diverged: this set re-runs on the
                    # caller's sequential path (same contract as the
                    # all-device lockstep)
                    failed[k] = True
                    obs.count("lockstep.split_bt_fallback")
                    continue
                q = seq_sets[k][cursor[k]]
                wgt = weight_sets[k][cursor[k]]
                if f.get("rc"):
                    is_rc[k][cursor[k]] = True
                    q = _rc_encode(q)
                    wgt = wgt[::-1].copy()
                done_this_round.append((k, len(q)))
                fuse_read(k, res, q, wgt)

        _record_round(abpt, done_this_round, t_round)

    return [None if failed[k] else (graphs[k], is_rc[k]) for k in range(K)]


def _record_round(abpt: Params, done: List[Tuple[int, int]],
                  t_round: float) -> None:
    """Amortized per-read latency records (the lockstep contract: a share
    of the round wall per advanced read, flagged amortized)."""
    if not done:
        return
    from .. import obs
    from ..pipeline import _band_cols
    share = (time.perf_counter() - t_round) / len(done)
    for _k, qlen in done:
        obs.record_read(share, qlen, _band_cols(abpt, qlen),
                        abpt.device, amortized=True)
