"""Split lockstep: K read sets advance one read per round — host fusion
interleaved with ONE batched device DP dispatch per round.

This is ROUND8_NOTES.md's rewrite #2 ("split fusion out of the vmapped
region entirely"): the all-device lockstep (fused_loop.
progressive_poa_fused_batch) pays the vmapped fusion scatters and the
vmapped while_loop's full-plane selects on every read — measured 1.37x
SLOWER than serial at K=4 on CPU hosts. Here each set's graph lives on the
HOST (the reference add_alignment fusion, byte-golden engine), and only the
banded DP scan + backtrack carry the K axis (align/dp_chunk.run_dp_chunk).

Continuous batching (PR 17): because fusion is a host-side step between
rounds, the lane population can legally change at every round boundary.
The driver keeps a LANE TABLE instead of fixed parallel arrays: a finished
or backtrack-diverged lane RETIRES immediately (its result goes to its
future via the churn hook instead of padding the group as a born-finished
no-op), and same-Qp-rung JOINERS board freed lanes mid-flight. Repacking
rides the existing pow2 K rungs (`Kb = k_rung(len(dp_ks))` is recomputed
per round anyway), so churn creates no new compile rungs. Per-round lane
occupancy (live lanes / group capacity) feeds
`scheduler.observe_lane_occupancy` — the measured replacement for the
reactive noop EWMA.

Byte parity: per read this is exactly pipeline.poa's sequence (DP at the
pre-fusion graph, optional ambiguous-strand RC retry with the host float
threshold, host add_alignment fusion), so outputs are byte-identical to
the sequential host loop for any K, any set mix, and any join/retire
schedule — a lane's reads never see the other lanes' graphs.
"""
from __future__ import annotations

import os
import time
from typing import List, Optional, Tuple

import numpy as np

from ..params import Params

MAX_W_GROWTH = 6


class ChurnHook:
    """Round-boundary lane-churn protocol for progressive_poa_split_batch.

    ``on_round(round_i, live_sids)`` is called before each round (round_i
    counts from 1) and returns ``(evict_sids, joiners)``: lanes to drop
    without a result (deadline expired — the hook owns answering them) and
    new sets to board as ``(sid, seqs, weights)`` tuples. Joiners must be
    on the group's Qp rung (every read qlen + 2 <= Qp); violators are
    rejected via ``on_retire(sid, None, round_i)``.

    ``on_retire(sid, result, round_i)`` delivers a lane's result the round
    it finishes: ``(host_graph, is_rc_flags)``, or ``None`` when the lane
    must re-run on the caller's sequential path (backtrack divergence).
    """

    def on_round(self, round_i: int, live_sids: list) -> tuple:
        return set(), []

    def on_retire(self, sid, result, round_i: int) -> None:  # pragma: no cover
        pass


class _Lane:
    __slots__ = ("sid", "seqs", "weights", "graph", "is_rc", "cursor",
                 "n_reads", "join_round")

    def __init__(self, sid, seqs, weights, graph, join_round):
        self.sid = sid
        self.seqs = seqs
        self.weights = weights
        self.graph = graph
        self.is_rc = [False] * len(seqs)
        self.cursor = 0
        self.n_reads = len(seqs)
        self.join_round = join_round


def _round_delay_s() -> float:
    """Test shim: per-round sleep so serve e2e tests can land a joiner at a
    deterministic round boundary."""
    try:
        return float(os.environ.get("ABPOA_TPU_LOCKSTEP_ROUND_DELAY_S", "0"))
    except ValueError:
        return 0.0


def progressive_poa_split_batch(seq_sets: List[List[np.ndarray]],
                                weight_sets: List[List[np.ndarray]],
                                abpt: Params,
                                churn: Optional[ChurnHook] = None,
                                mesh=None) -> list:
    """Run K independent read sets in split lockstep.

    Returns one entry per INITIAL set: `(host_graph, is_rc_flags)`, or
    `None` where that set must re-run on the caller's sequential path
    (device backtrack divergence) — the same contract as
    progressive_poa_fused_batch, so the two lockstep implementations are
    drop-in interchangeable at the flush_lockstep_group call site.

    With a `churn` hook the lane population may change at round
    boundaries: results (initial sets AND joiners) are additionally
    delivered through `churn.on_retire` the round each lane finishes, and
    `churn.on_round` may evict expired lanes or board same-rung joiners.

    `mesh` (a jax Mesh, parallel/shard.discover_mesh) spreads each round's
    single dispatch over the device mesh: the K rung rounds up to mesh
    divisibility and dispatch_dp_chunk shards the lane axis. Churn is
    untouched — lanes retire/join at round boundaries exactly as before,
    and the per-round contiguous repack plus dispatch-side padding IS the
    shard-local repack (padding lanes are born finished on whichever shard
    holds them).
    """
    from .. import obs
    from ..align.dp_chunk import (build_lockstep_tables, chunk_plane16,
                                  dispatch_dp_chunk, plan_degree_rung,
                                  plan_row_rung, result_from_chunk)
    from ..compile.ladder import k_rung, plan_chunk_buckets, qp_rung
    from ..graph import POAGraph
    from ..pipeline import _band_cols, _rc_encode
    from . import scheduler
    from .shard import mesh_size

    S = mesh_size(mesh)
    occ_route = "sharded" if S > 1 else "lockstep"
    K = len(seq_sets)
    qmax = max((len(s) for ss in seq_sets for s in ss), default=1)
    Qp = qp_rung(qmax)
    _qp, W, _local = plan_chunk_buckets(abpt, qmax)
    amb = bool(abpt.amb_strand)
    obs.observe("lockstep.k", K)
    delay_s = _round_delay_s()

    # the lane table: sid -> live lane, insertion-ordered (deterministic
    # dispatch packing); capacity is the high-water mark of concurrently
    # live lanes, so occupancy = live/capacity is comparable with the
    # static driver's (1 - noop) over the fixed group size
    lanes: dict = {}
    seen_sids = set()
    final: dict = {}
    initial_sids = list(range(K))
    for sid in initial_sids:
        lanes[sid] = _Lane(sid, seq_sets[sid], weight_sets[sid],
                           POAGraph(), 0)
        seen_sids.add(sid)
    capacity = max(len(lanes), 1)

    def retire(lane: _Lane, result, round_i: int) -> None:
        lanes.pop(lane.sid, None)
        if isinstance(lane.sid, int) and 0 <= lane.sid < K:
            final[lane.sid] = result
        if churn is not None:
            if lanes:
                obs.count("lockstep.early_retires")
            churn.on_retire(lane.sid, result, round_i)

    def fuse_read(lane: _Lane, res, qseq, weight) -> None:
        lane.graph.add_alignment(abpt, qseq, weight, None, res.cigar,
                                 lane.cursor, lane.n_reads, True)
        lane.cursor += 1

    round_i = 0
    while True:
        if delay_s:
            time.sleep(delay_s)
        if churn is not None:
            evict, joiners = churn.on_round(round_i + 1, list(lanes))
            for sid in evict or ():
                if lanes.pop(sid, None) is not None:
                    obs.count("lockstep.evictions")
            for sid, j_seqs, j_wgts in joiners or ():
                if sid in seen_sids:
                    raise ValueError(
                        f"split lockstep: duplicate lane sid {sid!r}")
                seen_sids.add(sid)
                j_qmax = max((len(s) for s in j_seqs), default=1)
                if not j_seqs or j_qmax + 2 > Qp:
                    # off-rung (or empty) joiner: never board it — it would
                    # force a new Qp rung. The hook re-routes it.
                    churn.on_retire(sid, None, round_i + 1)
                    continue
                if j_qmax > qmax:
                    qmax = j_qmax
                    _qp2, W2, _l2 = plan_chunk_buckets(abpt, qmax)
                    W = max(W, W2)
                lanes[sid] = _Lane(sid, j_seqs, j_wgts, POAGraph(),
                                   round_i + 1)
                obs.count("lockstep.joins")
            capacity = max(capacity, len(lanes))
        if not lanes:
            break
        t_round = time.perf_counter()
        obs.rounds.begin_round()
        round_i += 1
        obs.count("lockstep.chunks")
        # measured lane occupancy: live lanes over the group's high-water
        # capacity — the scheduler's K-cap input (noop = 1 - occupancy)
        active = list(lanes.values())
        occ = len(active) / capacity
        obs.observe("lockstep.noop_set_fraction", 1.0 - occ)
        scheduler.observe_lane_occupancy(occ, route=occ_route)
        if occ < 1.0:
            obs.count("lockstep.drain_chunks")

        # first read of a lane seeds its graph: fusion only, no DP
        from ..align.result import AlignResult
        dp_lanes: List[_Lane] = []
        done_this_round: List[Tuple[int, int]] = []  # (sid, qlen) advanced
        for lane in active:
            if lane.graph.node_n <= 2:
                with obs.phase("fusion"):
                    done_this_round.append(
                        (lane.sid, len(lane.seqs[lane.cursor])))
                    fuse_read(lane, AlignResult(), lane.seqs[lane.cursor],
                              lane.weights[lane.cursor])
                if lane.cursor >= lane.n_reads:
                    retire(lane, (lane.graph, lane.is_rc), round_i)
            else:
                dp_lanes.append(lane)
        if not dp_lanes:
            _record_round(abpt, done_this_round, t_round, route=occ_route,
                          lanes=len(active), k_cap=capacity, mesh=S)
            continue

        with obs.phase("align"):
            tables = []
            for lane in dp_lanes:
                q = lane.seqs[lane.cursor]
                obs.record_dp(lane.graph.node_n, _band_cols(abpt, len(q)),
                              abpt.gap_mode)
                tables.append(build_lockstep_tables(lane.graph, abpt, q, Qp))
            R = plan_row_rung(max(t["n_rows"] for t in tables))
            P = plan_degree_rung(max(t["pre_idx"].shape[1] for t in tables))
            Kb = k_rung(len(dp_lanes), S)
            plane16 = chunk_plane16(
                abpt, qmax, max(t["n_rows"] for t in tables))
            # the W-growth retry wraps BOTH dispatches: a band overflow on
            # either strand (result_from_chunk returns an empty cigar for
            # it) regrows W and replays the round — an overflowed result
            # must never reach fusion
            for _g in range(MAX_W_GROWTH + 1):
                packed = dispatch_dp_chunk(abpt, tables, Kb, R, P, Qp, W,
                                           plane16, mesh=mesh)
                results = [result_from_chunk(
                    abpt, packed[i], tables[i],
                    lane.graph.index_to_node_id) for i, lane in
                    enumerate(dp_lanes)]
                overflowed = any(f["overflow"] for _res, f in results)
                if amb and not overflowed:
                    # ambiguous-strand rescue, host threshold exactly as
                    # pipeline.poa: a sub-threshold forward score retries
                    # the reverse complement against the SAME tables (the
                    # graph is untouched until fusion) in one extra
                    # batched dispatch
                    rc_is = []
                    for i, lane in enumerate(dp_lanes):
                        res, _f = results[i]
                        q = lane.seqs[lane.cursor]
                        thr = (min(len(q), lane.graph.node_n - 2)
                               * abpt.max_mat * 0.3333)
                        if res.best_score < thr:
                            rc_is.append(i)
                    if rc_is:
                        rc_tables = []
                        for i in rc_is:
                            lane = dp_lanes[i]
                            q = lane.seqs[lane.cursor]
                            rc_q = _rc_encode(q)
                            obs.record_dp(lane.graph.node_n,
                                          _band_cols(abpt, len(rc_q)),
                                          abpt.gap_mode)
                            t = dict(tables[i])
                            qp = np.zeros_like(t["qp"])
                            query_pad = np.zeros_like(t["query"])
                            if len(rc_q):
                                qp[:, 1: len(rc_q) + 1] = abpt.mat[:, rc_q]
                                query_pad[:len(rc_q)] = rc_q
                            t["qp"] = qp
                            t["query"] = query_pad
                            rc_tables.append(t)
                        rc_packed = dispatch_dp_chunk(abpt, rc_tables, Kb,
                                                      R, P, Qp, W, plane16,
                                                      mesh=mesh)
                        for j, i in enumerate(rc_is):
                            lane = dp_lanes[i]
                            rc_res, rc_f = result_from_chunk(
                                abpt, rc_packed[j], rc_tables[j],
                                lane.graph.index_to_node_id)
                            if rc_f["overflow"]:
                                overflowed = True
                            elif rc_f["bt_err"]:
                                results[i] = (results[i][0],
                                              {"overflow": False,
                                               "bt_err": True})
                            elif (rc_res.best_score
                                  > results[i][0].best_score):
                                results[i] = (rc_res,
                                              {"overflow": False,
                                               "bt_err": False,
                                               "rc": True})
                if not overflowed:
                    break
                W *= 2
                obs.count("fused.grow.band")
            else:
                raise RuntimeError(
                    "split lockstep: band growth did not converge")

        with obs.phase("fusion"):
            for i, lane in enumerate(dp_lanes):
                res, f = results[i]
                if f["bt_err"]:
                    # device backtrack diverged: this set re-runs on the
                    # caller's sequential path (same contract as the
                    # all-device lockstep) — retired NOW, not at group end
                    obs.count("lockstep.split_bt_fallback")
                    retire(lane, None, round_i)
                    continue
                q = lane.seqs[lane.cursor]
                wgt = lane.weights[lane.cursor]
                if f.get("rc"):
                    lane.is_rc[lane.cursor] = True
                    q = _rc_encode(q)
                    wgt = wgt[::-1].copy()
                done_this_round.append((lane.sid, len(q)))
                fuse_read(lane, res, q, wgt)
                if lane.cursor >= lane.n_reads:
                    # finished lanes retire at the round boundary they
                    # finish: result to its future, slot freed for joiners
                    retire(lane, (lane.graph, lane.is_rc), round_i)

        _record_round(abpt, done_this_round, t_round, route=occ_route,
                      lanes=len(active), k_cap=capacity, mesh=S)

    return [final.get(sid) for sid in initial_sids]


def _record_round(abpt: Params, done: List[Tuple[int, int]],
                  t_round: float, route: str = "lockstep", lanes: int = 0,
                  k_cap: int = 1, mesh: int = 1) -> None:
    """Amortized per-read latency records (the lockstep contract: a share
    of the round wall per advanced read, flagged amortized), plus the
    round's sample into the obs/rounds.py timeline ring (round wall,
    dispatch wall, live lanes, per-shard split)."""
    from .. import obs
    wall = time.perf_counter() - t_round
    obs.rounds.record_round(route, lanes, k_cap, wall, mesh=mesh)
    if not done:
        return
    from ..pipeline import _band_cols
    share = wall / len(done)
    for _k, qlen in done:
        obs.record_read(share, qlen, _band_cols(abpt, qlen),
                        abpt.device, amortized=True)
