from .fastx import read_fastx, SeqRecord
from .gaf import gaf_record, merged_cigar_str
