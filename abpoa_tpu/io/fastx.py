"""FASTA/FASTQ streaming reader (gzip-transparent).

Replaces the reference's kseq.h; same record model: name, comment, seq, qual.
"""
from __future__ import annotations

import gzip
from dataclasses import dataclass
from typing import Iterator, List, Optional


@dataclass
class SeqRecord:
    name: str
    comment: str
    seq: str
    qual: Optional[str] = None
    is_rc: bool = False


def _open(path: str):
    fp = open(path, "rb")
    magic = fp.read(2)
    fp.seek(0)
    if magic == b"\x1f\x8b":
        return gzip.open(fp, "rt")
    return open(path, "rt")


def iter_fastx_handle(fp) -> Iterator[SeqRecord]:
    """Parse FASTA/FASTQ records from an open text handle (a file, or a
    StringIO over an `abpoa-tpu serve` request body).

    Hardened against the malformed inputs the quarantine fuzz grid
    feeds it (tests/test_resilience.py): CRLF line endings are stripped
    everywhere (a '\\r' left in a sequence would silently encode as an
    ambiguous base), and a FASTQ record truncated at EOF yields its
    partial fields as-is — `resilience.validate_records` then rejects the
    set with a structured per-set error instead of a wrong consensus."""
    name = comment = None
    seq_parts: List[str] = []
    in_qual = False
    for line in fp:
        line = line.rstrip("\r\n")
        if not line and not in_qual:
            continue
        if line.startswith(">") or (line.startswith("@") and not in_qual and name is None):
            if name is not None:
                yield SeqRecord(name, comment or "", "".join(seq_parts), None)
            head = line[1:].split(None, 1)
            name = head[0] if head else ""
            comment = head[1] if len(head) > 1 else ""
            seq_parts, in_qual = [], False
            is_fq = line.startswith("@")
            if is_fq:
                # FASTQ: strict 4-line records (readline() returns ""
                # past EOF, so a truncated record yields short fields
                # for validation to reject — never an exception here)
                seq = fp.readline().rstrip("\r\n")
                fp.readline()  # '+'
                qual = fp.readline().rstrip("\r\n")
                yield SeqRecord(name, comment or "", seq, qual)
                name = None
        else:
            seq_parts.append(line)
    if name is not None:
        yield SeqRecord(name, comment or "", "".join(seq_parts), None)


def iter_fastx(path: str) -> Iterator[SeqRecord]:
    with _open(path) as fp:
        yield from iter_fastx_handle(fp)


def read_fastx(path: str) -> List[SeqRecord]:
    from ..obs import phase
    with phase("fastx_parse"):
        return list(iter_fastx(path))


def read_fastx_text(text: str) -> List[SeqRecord]:
    """Records from in-memory FASTA/FASTQ text (the serve request-body
    path) — same parser, same hardening, no filesystem."""
    import io
    return list(iter_fastx_handle(io.StringIO(text)))
