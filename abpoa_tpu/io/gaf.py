"""GAF-style alignment records for the map workload (minigraph/vg GAF).

One tab-separated line per read against the static graph:

    qname qlen qstart qend strand path plen pstart pend
    matches block_len mapq  AS:i:<score>  cg:Z:<cigar>

Every field derives from the packed graph cigar (`cigar.py`), the encoded
read and the graph's per-node bases — NOT from engine-internal state — so
two engines that produce the same cigar produce byte-identical records.
That is the map gate's oracle contract: device-vs-numpy equality reduces
to cigar equality, and the GAF line is the witness.

Conventions (documented, deterministic):
- the graph is node-per-base, so `path` is one ">"-prefixed node id per
  aligned graph base in walk order (M and D ops), and plen == |path| with
  pstart 0, pend plen — the path IS the aligned subwalk;
- `strand` is "+" unless the amb-strand rescue chose the reverse
  complement; qstart/qend and the cigar are on the ALIGNED orientation;
- `matches` recounts M ops whose graph base equals the query base (the
  backtrack folds mismatches into M, reference abPOA semantics), so it
  never trusts a head counter that an oracle path might not fill;
- mapq is 255 (unavailable: map mode does not chain or rescore);
- cg:Z: is the run-merged cigar (M/I/D; X only if a CDIFF op appears).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .. import constants as C

_OP_CHAR = {C.CMATCH: "M", C.CINS: "I", C.CDEL: "D", C.CDIFF: "X",
            C.CSOFT_CLIP: "S", C.CHARD_CLIP: "H"}


def _unpack(p: int):
    """-> (op, node_id, query_id, run_len) for one packed entry; fields
    that the op does not carry come back as -1/run_len semantics per
    cigar.py's packing table."""
    op = p & 0xF
    if op in (C.CMATCH, C.CDIFF):
        return op, p >> 34, (p >> 4) & 0x3FFFFFFF, 1
    if op == C.CDEL:
        return op, p >> 34, -1, (p >> 4) & 0x3FFFFFFF
    # I/S/H: query_id << 34 | run_len << 4
    return op, -1, p >> 34, (p >> 4) & 0x3FFFFFFF


def merged_cigar_str(cigar: List[int]) -> str:
    """Run-merged cigar text (`2300M12I1D...`) from the packed per-base
    list — the cg:Z: tag body. Empty cigar renders as "*"."""
    if not cigar:
        return "*"
    out: List[str] = []
    run_op, run_len = None, 0
    for p in cigar:
        op, _nid, _qid, ln = _unpack(p)
        ch = _OP_CHAR[op]
        if ch == run_op:
            run_len += ln
        else:
            if run_op is not None:
                out.append(f"{run_len}{run_op}")
            run_op, run_len = ch, ln
    out.append(f"{run_len}{run_op}")
    return "".join(out)


def gaf_record(qname: str, query: np.ndarray, res,
               base_by_nid: np.ndarray, strand: str = "+",
               comment: Optional[str] = None) -> str:
    """One GAF line for `res` (AlignResult with a packed cigar) of encoded
    read `query` (aligned orientation). `base_by_nid` maps node id ->
    encoded base (StaticGraphTables.base_by_nid)."""
    qlen = len(query)
    cigar = res.cigar or []
    path: List[str] = []
    matches = 0
    block_len = 0
    qstart, qend = -1, -1
    for p in cigar:
        op, nid, qid, ln = _unpack(p)
        block_len += ln
        if op in (C.CMATCH, C.CDIFF):
            path.append(f">{nid}")
            if qstart < 0:
                qstart = qid
            qend = qid + 1
            if 0 <= qid < qlen and nid < len(base_by_nid) \
                    and int(base_by_nid[nid]) == int(query[qid]):
                matches += 1
        elif op == C.CDEL:
            path.extend(f">{nid}" for _ in range(ln))
    plen = len(path)
    if qstart < 0:
        # no aligned base: an unmapped-style record, path "*"
        qstart = qend = 0
    fields = [
        qname, str(qlen), str(qstart), str(qend), strand,
        "".join(path) if path else "*",
        str(plen), "0", str(plen),
        str(matches), str(block_len), "255",
        f"AS:i:{int(res.best_score)}",
        f"cg:Z:{merged_cigar_str(cigar)}",
    ]
    if comment:
        fields.append(f"co:Z:{comment}")
    return "\t".join(fields)
