"""abpoa-compatible command-line interface (reference src/abpoa.c)."""
from __future__ import annotations

import argparse
import os
import sys
import time

from . import __version__
from . import constants as C
from .params import Params
from .pipeline import Abpoa, msa_from_file


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="abpoa-tpu",
        description="abpoa-tpu: TPU-native adaptive banded Partial Order Alignment",
        add_help=False,
    )
    p.add_argument("input", nargs="?", help="input FASTA/FASTQ (or file list with -l)")
    p.add_argument("-m", "--aln-mode", type=int, default=C.GLOBAL_MODE)
    p.add_argument("-M", "--match", type=int, default=C.DEFAULT_MATCH)
    p.add_argument("-X", "--mismatch", type=int, default=C.DEFAULT_MISMATCH)
    p.add_argument("-t", "--matrix", type=str, default=None)
    p.add_argument("-O", "--gap-open", type=str, default=None)
    p.add_argument("-E", "--gap-ext", type=str, default=None)
    p.add_argument("-b", "--extra-b", type=int, default=C.EXTRA_B)
    p.add_argument("-f", "--extra-f", type=float, default=C.EXTRA_F)
    p.add_argument("-z", "--zdrop", type=int, default=-1)
    p.add_argument("-e", "--bonus", type=int, default=-1)
    p.add_argument("-G", "--inc-path-score", action="store_true")
    p.add_argument("-L", "--sort-by-len", action="store_true")
    p.add_argument("-R", "--gap-on-right", action="store_true")
    p.add_argument("-J", "--gap-at-end", action="store_true")
    p.add_argument("-Q", "--use-qual-weight", action="store_true")
    p.add_argument("-S", "--seeding", action="store_true")
    p.add_argument("-k", "--k-mer", type=int, default=C.DEFAULT_MMK)
    p.add_argument("-w", "--window", type=int, default=C.DEFAULT_MMW)
    p.add_argument("-n", "--min-poa-win", type=int, default=C.DEFAULT_MIN_POA_WIN)
    p.add_argument("-p", "--progressive", action="store_true")
    p.add_argument("-c", "--amino-acid", action="store_true")
    p.add_argument("-l", "--in-list", action="store_true")
    p.add_argument("-i", "--increment", type=str, default=None)
    p.add_argument("-s", "--amb-strand", action="store_true")
    p.add_argument("-o", "--output", type=str, default=None)
    p.add_argument("-r", "--result", type=int, default=C.OUT_CONS)
    p.add_argument("-g", "--out-pog", type=str, default=None)
    p.add_argument("-a", "--cons-algrm", type=int, default=C.CONS_HB)
    p.add_argument("-d", "--maxnum-cons", type=int, default=1)
    p.add_argument("-q", "--min-freq", type=float, default=C.MULTIP_MIN_FREQ)
    p.add_argument("-h", "--help", action="help")
    p.add_argument("-v", "--version", action="version", version=__version__)
    p.add_argument("-V", "--verbose", type=int, default=0)
    p.add_argument("--device", type=str, default="auto",
                   help="DP backend: auto | numpy | native | jax | pallas "
                        "[auto: accelerator if reachable, else native C++, "
                        "else numpy]")
    p.add_argument("--lockstep", type=str, default="auto",
                   choices=["auto", "on", "off"],
                   help="vmapped lockstep batching for -l multi-set runs: "
                        "auto = only on a real accelerator mesh (serial "
                        "K=1 is faster on CPU, see ROUND8_NOTES.md); "
                        "on/off force it [%(default)s]")
    p.add_argument("--workers", type=int, default=0, metavar="N",
                   help="supervised worker PROCESSES for -l multi-set "
                        "runs (crash containment, hard-kill deadlines, "
                        "poison-job quarantine — parallel/pool.py): "
                        "0 = auto (one per core on multicore CPU hosts), "
                        "1 = in-process serial "
                        "[ABPOA_TPU_WORKERS or %(default)s]")
    p.add_argument("--mesh", type=int, default=None, metavar="N",
                   help="shard each split-lockstep/map round over an "
                        "N-device lane mesh (the scheduler's sharded "
                        "route; global K = N x the per-chip cap; 1-core "
                        "hosts get the virtual CPU mesh only on this "
                        "explicit request) [ABPOA_TPU_MESH]")
    p.add_argument("--report", type=str, default=None, metavar="FILE",
                   help="write a structured JSON run report (versioned "
                        "schema: phase wall-times, dispatch/fallback/"
                        "recompile counters, DP-cell totals, MFU estimate) "
                        "to FILE ('-' for stdout; falls to stderr when "
                        "stdout carries the consensus)")
    p.add_argument("--profile-dir", type=str, default=None, metavar="DIR",
                   help="capture a jax.profiler (XProf/TensorBoard) trace "
                        "around device dispatches into DIR")
    p.add_argument("--trace", type=str, default=None, metavar="FILE",
                   help="write a Chrome trace-event JSON timeline (phases, "
                        "per-read/per-chunk/per-window spans, XLA compiles) "
                        "to FILE ('-' for stdout; falls to stderr when "
                        "stdout carries the consensus). Open in Perfetto "
                        "(ui.perfetto.dev) or chrome://tracing")
    p.add_argument("--metrics", type=str, nargs="?", metavar="FILE",
                   default=None, const="",
                   help="maintain a Prometheus text-exposition file "
                        "(atomic renames, ~1s refresh) while the run "
                        "executes — the feed for `abpoa-tpu top` and any "
                        "node_exporter textfile collector "
                        "[FILE defaults to ~/.cache/abpoa_tpu/metrics.prom]")
    p.add_argument("--metrics-port", type=int, default=None, metavar="N",
                   help="additionally serve /metrics on 127.0.0.1:N "
                        "(stdlib http.server) for the duration of the run")
    return p


def apply_gap_args(abpt: Params, gap_open, gap_ext) -> None:
    """Parse the -O/-E "o1[,o2]"/"e1[,e2]" forms (shared with `serve`)."""
    if gap_open is not None:
        parts = gap_open.split(",")
        abpt.gap_open1 = int(parts[0])
        abpt.gap_open2 = int(parts[1]) if len(parts) > 1 else 0
    if gap_ext is not None:
        parts = gap_ext.split(",")
        abpt.gap_ext1 = int(parts[0])
        abpt.gap_ext2 = int(parts[1]) if len(parts) > 1 else 0


def apply_result_mode(abpt: Params, r: int) -> bool:
    """Decode the -r output mode onto `abpt` (shared with `serve`);
    returns False for an unknown mode."""
    if r == C.OUT_CONS:
        abpt.out_cons, abpt.out_msa = True, False
    elif r == C.OUT_MSA:
        abpt.out_cons, abpt.out_msa = False, True
    elif r == C.OUT_CONS_MSA:
        abpt.out_cons = abpt.out_msa = True
    elif r == C.OUT_GFA:
        abpt.out_cons, abpt.out_gfa = False, True
    elif r == C.OUT_CONS_GFA:
        abpt.out_cons = abpt.out_gfa = True
    elif r == C.OUT_CONS_FQ:
        abpt.out_cons = abpt.out_fq = True
    else:
        return False
    return True


def args_to_params(args: argparse.Namespace) -> Params:
    abpt = Params()
    abpt.align_mode = args.aln_mode
    abpt.match = args.match
    abpt.mismatch = args.mismatch
    if args.matrix:
        abpt.use_score_matrix = True
        abpt.mat_fn = args.matrix
    apply_gap_args(abpt, args.gap_open, args.gap_ext)
    abpt.wb = args.extra_b
    abpt.wf = args.extra_f
    abpt.zdrop = args.zdrop
    abpt.end_bonus = args.bonus
    abpt.inc_path_score = args.inc_path_score
    abpt.sort_input_seq = args.sort_by_len
    abpt.put_gap_on_right = args.gap_on_right
    abpt.put_gap_at_end = args.gap_at_end
    abpt.use_qv = args.use_qual_weight
    abpt.disable_seeding = not args.seeding
    abpt.k = args.k_mer
    abpt.w = args.window
    abpt.min_w = args.min_poa_win
    abpt.progressive_poa = args.progressive
    if args.amino_acid:
        abpt.m = 27
    abpt.incr_fn = args.increment
    abpt.amb_strand = args.amb_strand
    if not apply_result_mode(abpt, args.result):
        print(f"Error: unknown output result mode: {args.result}.",
              file=sys.stderr)
    abpt.out_pog = args.out_pog
    abpt.cons_algrm = args.cons_algrm
    if not 1 <= args.maxnum_cons <= 10:
        raise SystemExit("Error: max number of consensus sequences should be 1~10.")
    abpt.max_n_cons = args.maxnum_cons
    abpt.min_freq = args.min_freq
    abpt.verbose = args.verbose
    abpt.device = args.device
    abpt.lockstep = args.lockstep
    if args.workers < 0:
        raise SystemExit("Error: --workers must be >= 0 (0 = auto).")
    abpt.workers = args.workers
    if getattr(args, "mesh", None) is not None:
        if args.mesh < 0:
            raise SystemExit("Error: --mesh must be >= 0 (0 = off).")
        # ONE grammar: the env var is the definition site every consumer
        # reads (scheduler.plan_route via shard.requested_mesh_size), so
        # the flag just sets it before any route is planned
        os.environ["ABPOA_TPU_MESH"] = str(args.mesh)
    return abpt


def report_main(argv) -> int:
    """`abpoa-tpu report FILE` — render a `--report` JSON as a one-screen
    phase/counter/percentile table; `abpoa-tpu report --diff A B`
    compares two reports field by field (delta + percent change).
    tools/report_view.py is the same entry for checkouts without the
    console script installed."""
    import json
    from .obs.report import render_report, render_report_diff
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: abpoa-tpu report FILE [FILE ...]\n"
              "       abpoa-tpu report --diff A B\n\n"
              "render --report JSON run reports as human-readable tables "
              "('-' reads stdin); --diff compares two reports "
              "(phase walls, reads/s, CUPS, compiles, faults) with "
              "per-field delta and percent change", file=sys.stderr)
        return 0 if argv else 1

    def load(path):
        with (sys.stdin if path == "-" else open(path)) as fp:
            return json.load(fp)

    if argv[0] == "--diff":
        if len(argv) != 3:
            print("Error: --diff needs exactly two report files.",
                  file=sys.stderr)
            return 2
        sys.stdout.write(render_report_diff(load(argv[1]), load(argv[2]),
                                            label_a=argv[1],
                                            label_b=argv[2]))
        return 0
    for i, path in enumerate(argv):
        rep = load(path)
        if len(argv) > 1:
            print(("" if i == 0 else "\n") + f"== {path} ==")
        sys.stdout.write(render_report(rep))
    return 0


def warm_main(argv) -> int:
    """`abpoa-tpu warm [--ladder quick|full]` — AOT-precompile the declared
    bucket ladder (compile/ladder.py) and populate the persistent XLA
    compilation cache, so subsequent runs — this process, the bench, a
    fresh server start — pay cache loads instead of first-sight compiles."""
    import argparse
    import json
    ap = argparse.ArgumentParser(
        prog="abpoa-tpu warm",
        description="AOT-precompile the shape-bucket ladder and fill the "
                    "persistent XLA compilation cache "
                    "(~/.cache/abpoa_tpu/xla; override with "
                    "ABPOA_TPU_XLA_CACHE_DIR, disable with "
                    "ABPOA_TPU_XLA_CACHE=0)")
    ap.add_argument("--ladder", choices=["quick", "full"], default="quick",
                    help="rung tier: quick = smoke + 2 kb serve shapes; "
                         "full = + 10 kb north-star, lockstep and "
                         "seeded-window shapes [%(default)s]")
    ap.add_argument("--device", default="jax",
                    help="backend to warm statics for: jax | pallas "
                         "[%(default)s]")
    ap.add_argument("--report", default=None, metavar="FILE",
                    help="write the warm summary JSON to FILE "
                         "('-' for stdout)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the per-signature progress lines")
    args = ap.parse_args(argv)
    from .utils.probe import apply_platform_pin, jax_backend_reachable
    if not jax_backend_reachable():
        print("Error: JAX backend probe timed out (wedged accelerator "
              "tunnel?); nothing to warm.", file=sys.stderr)
        return 1
    apply_platform_pin()
    from . import obs
    from .compile import warm_ladder
    obs.start_run()
    abpt = Params()
    abpt.device = args.device
    abpt.finalize()
    summary = warm_ladder(tier=args.ladder, abpt=abpt, verbose=not args.quiet)
    print(f"[abpoa-tpu warm] {summary['signatures']} signatures "
          f"({summary['compiled']} compiled, "
          f"{summary['persistent_cache_hits']} persistent-cache hits, "
          f"{summary['xla_compile_s']}s in XLA) in {summary['wall_s']}s; "
          f"cache: {summary['cache_dir']}", file=sys.stderr)
    # warm is a perf-bearing run: cold-start readiness is a trajectory
    # metric too (a compile-cache regression shows up here first)
    obs.ledger.append_record(obs.ledger.make_record(
        "warm", workload=f"ladder:{args.ladder}", device=args.device,
        compile_misses=summary.get("compiled"),
        extra={"signatures": summary.get("signatures"),
               "persistent_cache_hits": summary.get(
                   "persistent_cache_hits"),
               "xla_compile_s": summary.get("xla_compile_s"),
               "wall_s": summary.get("wall_s")}))
    if args.report:
        fp = sys.stdout if args.report == "-" else open(args.report, "w")
        try:
            json.dump(summary, fp, indent=2)
            fp.write("\n")
        finally:
            if fp is not sys.stdout:
                fp.close()
    return 0


def map_main(argv) -> int:
    """`abpoa-tpu map -g GRAPH reads.fq` — fixed-graph read-to-graph
    mapping: restore the graph ONCE (GFA S/P lines or MSA FASTA, the same
    ingest as -i), build its immutable DP tables once, stream every read
    against it in vmapped pow2 batches (parallel/map_driver.py) and emit
    one GAF record per read (io/gaf.py). The graph is never mutated and
    no consensus is produced — a pure-throughput workload."""
    ap = argparse.ArgumentParser(
        prog="abpoa-tpu map",
        description="map reads against a fixed restored graph; one "
                    "GAF-style record per read on stdout (or -o FILE)")
    ap.add_argument("reads", help="FASTA/FASTQ reads to map")
    ap.add_argument("-g", "--graph", required=True, metavar="FILE",
                    help="graph to map against: abPOA GFA (S/P lines) or "
                         "MSA FASTA with '-' gaps — the -i restore formats")
    ap.add_argument("-o", "--output", type=str, default=None,
                    help="GAF output file [stdout]")
    ap.add_argument("-M", "--match", type=int, default=C.DEFAULT_MATCH)
    ap.add_argument("-X", "--mismatch", type=int, default=C.DEFAULT_MISMATCH)
    ap.add_argument("-O", "--gap-open", type=str, default=None)
    ap.add_argument("-E", "--gap-ext", type=str, default=None)
    ap.add_argument("-b", "--extra-b", type=int, default=C.EXTRA_B)
    ap.add_argument("-f", "--extra-f", type=float, default=C.EXTRA_F)
    ap.add_argument("-s", "--amb-strand", action="store_true",
                    help="rescue sub-threshold reads via their reverse "
                         "complement (strand '-' in the GAF record)")
    ap.add_argument("-K", "--k-cap", type=int, default=0, metavar="N",
                    help="read-batch lane cap (0 = planned: the lockstep "
                         "group size under the measured-occupancy cap)")
    ap.add_argument("--device", type=str, default="auto",
                    help="DP backend: auto | numpy | jax | pallas")
    ap.add_argument("--mesh", type=int, default=None, metavar="N",
                    help="shard the read batch over an N-device mesh "
                         "(sharded route: global K = N x per-chip cap; "
                         "on a 1-core host an explicit request builds the "
                         "virtual CPU mesh) [ABPOA_TPU_MESH]")
    ap.add_argument("-V", "--verbose", type=int, default=0)
    ap.add_argument("--report", type=str, default=None, metavar="FILE")
    ap.add_argument("--trace", type=str, default=None, metavar="FILE")
    ap.add_argument("--metrics", type=str, nargs="?", metavar="FILE",
                    default=None, const="")
    ap.add_argument("--metrics-port", type=int, default=None, metavar="N")
    args = ap.parse_args(argv)
    if args.mesh is not None:
        if args.mesh < 0:
            print("Error: --mesh must be >= 0 (0 = off).", file=sys.stderr)
            return 1
        # one grammar: the env var is the definition site (shard.py reads it)
        os.environ["ABPOA_TPU_MESH"] = str(args.mesh)

    abpt = Params()
    abpt.match = args.match
    abpt.mismatch = args.mismatch
    apply_gap_args(abpt, args.gap_open, args.gap_ext)
    abpt.wb = args.extra_b
    abpt.wf = args.extra_f
    abpt.amb_strand = args.amb_strand
    abpt.verbose = args.verbose
    abpt.device = args.device
    try:
        abpt.finalize()
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    from . import obs
    obs.start_run()
    metrics_path = http_srv = None
    try:
        try:
            if args.metrics is not None:
                metrics_path = (args.metrics
                                or obs.metrics.default_textfile_path())
                os.makedirs(os.path.dirname(metrics_path) or ".",
                            exist_ok=True)
                obs.metrics.start_textfile_exporter(metrics_path)
            if args.metrics_port is not None:
                http_srv = obs.metrics.start_http_exporter(
                    args.metrics_port)
        except OSError as e:
            print(f"Error: metrics exporter: {e}", file=sys.stderr)
            return 1
        return _map_run(args, abpt)
    finally:
        if metrics_path is not None:
            obs.metrics.stop_textfile_exporter()
        if http_srv is not None:
            http_srv.shutdown()


def _map_run(args, abpt) -> int:
    import numpy as np
    from . import obs
    from .io import gaf_record, read_fastx
    from .parallel import (load_static_graph, map_read_host, map_reads_split,
                           plan_route)
    from .resilience import QUARANTINE_EXCEPTIONS
    from .utils import run_stats, set_verbose
    if args.trace:
        obs.trace_enable()
    set_verbose(abpt.verbose)
    t0 = time.time()
    c0 = time.process_time()
    rc = 0
    out_fp = (open(args.output, "w")
              if args.output and args.output != "-" else sys.stdout)
    try:
        try:
            with obs.phase("graph_restore"):
                _ab, static = load_static_graph(args.graph, abpt)
            records = read_fastx(args.reads)
        except QUARANTINE_EXCEPTIONS as e:
            print(f"Error: {type(e).__name__}: {e}", file=sys.stderr)
            return 1
        except ValueError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        encode = abpt.char_to_code
        queries = [
            encode[np.frombuffer(r.seq.encode(), dtype=np.uint8)
                   ].astype(np.uint8)
            for r in records]
        route = plan_route(abpt, len(queries), workload="map")
        if abpt.verbose:
            print(f"[abpoa_tpu::map] route {route.kind}: {route.reason}",
                  file=sys.stderr)
        if route.kind in ("map", "sharded"):
            mesh = None
            if route.kind == "sharded":
                # build the mesh before the first dispatch touches the
                # backend — the virtual CPU pin is a no-op after init
                from .parallel import discover_mesh
                mesh = discover_mesh(route.workers)
            k_cap = args.k_cap if args.k_cap > 0 else route.k_cap
            outcomes = map_reads_split(static, queries, abpt, k_cap=k_cap,
                                       mesh=mesh)
        else:
            # host route (no batched DP backend): the per-read oracle IS
            # the mapper; same records, same counters, serial wall
            outcomes = []
            g = static.graph
            for q in queries:
                t_r = time.perf_counter()
                with obs.phase("align"):
                    res, strand = map_read_host(g, abpt, q)
                obs.count("map.reads")
                obs.record_read(time.perf_counter() - t_r, len(q),
                                2 * len(q) + 1, abpt.device)
                outcomes.append((res, strand, None))
        n_mapped = 0
        for rec, q, outcome in zip(records, queries, outcomes):
            if outcome is None:
                # off-rung read (longer than the planned query rung):
                # structured stderr line, rc 1, stream continues
                print(f"Warning: read {rec.name!r} ({len(q)} bp) exceeds "
                      "the planned query rung; skipped.", file=sys.stderr)
                rc = 1
                continue
            res, strand, fallback = outcome
            out_fp.write(gaf_record(rec.name, q, res, static.base_by_nid,
                                    strand, comment=rec.comment or None)
                         + "\n")
            n_mapped += 1
        print(f"[abpoa_tpu::map] {n_mapped}/{len(records)} reads mapped "
              f"against {static.n_rows - 2}-node graph; {run_stats(t0, c0)}",
              file=sys.stderr)
    finally:
        if out_fp is not sys.stdout:
            out_fp.close()
    rep = obs.finalize_report()
    if args.report:
        if args.report == "-" and out_fp is sys.stdout:
            obs.write_report("-", rep=rep, fp=sys.stderr)
        else:
            obs.write_report(args.report, rep=rep)
    rec = obs.archive.summarize_report(rep, label=f"map:{args.reads}",
                                       device=abpt.device)
    # tagged like serve /map records: the SLO objectives scoped
    # `workload: map` judge this run against the map ceilings
    rec["workload"] = "map"
    obs.archive.append_record(rec)
    if args.trace:
        meta = {"input": args.reads, "graph": args.graph,
                "device": abpt.device}
        if args.trace == "-" and out_fp is sys.stdout:
            obs.export_chrome_trace("-", fp=sys.stderr, extra_meta=meta)
        else:
            obs.export_chrome_trace(args.trace, extra_meta=meta)
        obs.trace_disable()
    return rc


def main(argv=None) -> int:
    raw = sys.argv[1:] if argv is None else list(argv)
    if raw[:1] == ["report"]:
        return report_main(raw[1:])
    if raw[:1] == ["warm"]:
        return warm_main(raw[1:])
    if raw[:1] == ["map"]:
        return map_main(raw[1:])
    if raw[:1] == ["serve"]:
        from .serve import serve_main
        return serve_main(raw[1:])
    if raw[:1] == ["fleet"]:
        from .serve.fleet import fleet_main
        return fleet_main(raw[1:])
    if raw[:1] == ["slo"]:
        from .obs.slo import slo_main
        return slo_main(raw[1:])
    if raw[:1] == ["why"]:
        from .obs.why import why_main
        return why_main(raw[1:])
    if raw[:1] == ["top"]:
        from .obs.top import top_main
        return top_main(raw[1:])
    if raw[:1] == ["perf"]:
        from .obs.perf import perf_main
        return perf_main(raw[1:])
    args = build_parser().parse_args(raw)
    if args.input is None:
        build_parser().print_help(sys.stderr)
        return 1
    try:
        abpt = args_to_params(args).finalize()
    except ValueError as e:
        # parameter-contract violations (negative scores, the -E>=64
        # convex-gap bound, ...) are structured one-line errors, never
        # tracebacks — same contract as malformed input
        print(f"Error: {e}", file=sys.stderr)
        return 1
    from . import obs
    obs.start_run()
    metrics_path = http_srv = None
    try:
        # exporter startup INSIDE the try: if the HTTP bind fails after
        # the flusher already started, the finally still tears the
        # flusher down; startup failures (unwritable path, EADDRINUSE)
        # are the same structured one-line contract as bad parameters
        try:
            if args.metrics is not None:
                metrics_path = (args.metrics
                                or obs.metrics.default_textfile_path())
                os.makedirs(os.path.dirname(metrics_path) or ".",
                            exist_ok=True)
                obs.metrics.start_textfile_exporter(metrics_path)
            if args.metrics_port is not None:
                http_srv = obs.metrics.start_http_exporter(
                    args.metrics_port)
        except OSError as e:
            print(f"Error: metrics exporter: {e}", file=sys.stderr)
            return 1
        return _main_run(args, abpt, argv)
    finally:
        # exporter lifecycle must survive ANY mid-run exception (missing
        # -l list file, unwritable --report path, ...): a leaked flusher
        # thread would rewrite the textfile forever and a still-bound
        # --metrics-port would fail the retry with EADDRINUSE
        if metrics_path is not None:
            # final frame carries the finished run's gauges (breaker
            # state included: the breaker resets on the NEXT start_run)
            obs.metrics.stop_textfile_exporter()
        if http_srv is not None:
            http_srv.shutdown()


def _main_run(args, abpt, argv) -> int:
    """The alignment run proper (split from main() so the exporter
    teardown wraps it in one try/finally)."""
    from .utils import set_verbose, run_stats
    from . import obs
    if args.trace:
        obs.trace_enable()
    if args.profile_dir:
        obs.set_profile_dir(args.profile_dir)
    set_verbose(abpt.verbose)
    if abpt.verbose >= C.VERBOSE_INFO:
        print(f"[abpoa_tpu::main] CMD: {' '.join(argv or sys.argv)}", file=sys.stderr)
    out_fp = open(args.output, "w") if args.output and args.output != "-" else sys.stdout
    t0 = time.time()
    c0 = time.process_time()
    ab = Abpoa()
    rc = 0
    from .resilience import QUARANTINE_EXCEPTIONS
    try:
        if args.in_list:
            with open(args.input) as lf:
                files = [ln.strip() for ln in lf if ln.strip()]
            # run_batch lockstep-batches fused-eligible sets into one
            # vmapped device dispatch per group (reference -l loop,
            # src/abpoa.c:148-168, sequential there). Poisoned sets are
            # quarantined per set (structured stderr line + `faults`
            # record); the run exits 0 while any healthy set completed.
            from .parallel import run_batch
            stats = run_batch(files, abpt, out_fp)
            if stats["quarantined"]:
                print(f"[abpoa_tpu::main] {stats['quarantined']} of "
                      f"{stats['sets']} read sets quarantined "
                      "(see warnings above / --report faults)",
                      file=sys.stderr)
                if stats["quarantined"] >= stats["sets"]:
                    rc = 1  # nothing succeeded: that IS a failed run
        else:
            try:
                msa_from_file(ab, abpt, args.input, out_fp)
            except QUARANTINE_EXCEPTIONS as e:
                # single-set run: the same malformed-input/I/O-decay
                # classes the -l boundary quarantines become a structured
                # one-line error here (rc=1), never a traceback
                print(f"Error: {args.input}: {type(e).__name__}: {e}",
                      file=sys.stderr)
                rc = 1
    finally:
        if out_fp is not sys.stdout:
            out_fp.close()
    print(f"[abpoa_tpu::main] {run_stats(t0, c0)}", file=sys.stderr)
    rep = obs.finalize_report()
    if args.report:
        if args.report == "-" and out_fp is sys.stdout:
            # consensus already owns stdout; appending JSON would corrupt
            # the FASTA stream, so the report goes to stderr instead
            obs.write_report("-", rep=rep, fp=sys.stderr)
        else:
            obs.write_report(args.report, rep=rep)
    # cross-run archive (obs/archive.py): one compact JSONL record per
    # run, the window `abpoa-tpu slo` evaluates. Disabled by
    # ABPOA_TPU_ARCHIVE=0; failure to archive never fails the run.
    obs.archive.append_report(rep, label=args.input or "",
                              device=abpt.device)
    if args.trace:
        meta = {"input": args.input, "device": abpt.device}
        if args.trace == "-" and out_fp is sys.stdout:
            obs.export_chrome_trace("-", fp=sys.stderr, extra_meta=meta)
        else:
            obs.export_chrome_trace(args.trace, extra_meta=meta)
        # the tracer is process-global: disarm it so an in-process caller
        # (tests, library use) doesn't keep paying span overhead into a
        # stale ring after this run's export
        obs.trace_disable()
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
