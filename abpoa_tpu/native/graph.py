"""Python facade over the native host core.

Implements the POAGraph surface the pipeline needs; per-read fusion, topo sort
and kernel-table building run in C++. Output-time consumers (consensus, MSA,
GFA) get a materialized pure-Python POAGraph via `to_python()` — those run
once per read set, so the O(V+E) export cost is irrelevant.
"""
from __future__ import annotations

import ctypes
from typing import List, Optional

import numpy as np

from .. import constants as C
from ..params import Params
from . import load


def _ptr(a: np.ndarray, t):
    return a.ctypes.data_as(ctypes.POINTER(t))


class NativePOAGraph:
    is_native = True

    def __init__(self) -> None:
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native host core unavailable")
        self._h = self._lib.apg_create()
        self._version = 0
        self._index_cache_v = -1
        self._i2n: Optional[np.ndarray] = None
        self._n2i: Optional[np.ndarray] = None

    def __del__(self):
        try:
            self._lib.apg_destroy(self._h)
        except Exception:
            pass

    # ----------------------------------------------------------- properties
    @property
    def node_n(self) -> int:
        return self._lib.apg_node_n(self._h)

    @property
    def is_topological_sorted(self) -> bool:
        return bool(self._lib.apg_is_sorted(self._h))

    @is_topological_sorted.setter
    def is_topological_sorted(self, value: bool) -> None:
        # restore/reset paths clear this to force a re-sort; the C side
        # already cleared it on every mutation, so only honor False
        if value:
            raise ValueError("cannot force-mark a native graph as sorted")
        self._lib.apg_invalidate_sort(self._h)

    def reset(self) -> None:
        self._lib.apg_reset(self._h)
        self._version += 1

    def topological_sort(self, abpt: Params) -> None:
        self._lib.apg_topological_sort(
            self._h, 1 if abpt.wb >= 0 else 0, 1 if abpt.zdrop > 0 else 0)
        self._version += 1

    def _index_arrays(self):
        if self._index_cache_v != self._version:
            n = self.node_n
            self._i2n = np.zeros(n, dtype=np.int32)
            self._n2i = np.zeros(n, dtype=np.int32)
            self._lib.apg_get_index(self._h, _ptr(self._i2n, ctypes.c_int32),
                                    _ptr(self._n2i, ctypes.c_int32))
            self._index_cache_v = self._version
        return self._i2n, self._n2i

    @property
    def index_to_node_id(self) -> np.ndarray:
        return self._index_arrays()[0]

    @property
    def node_id_to_index(self) -> np.ndarray:
        return self._index_arrays()[1]

    # ------------------------------------------------------------- mutation
    def add_subgraph_alignment(self, abpt: Params, beg_node_id: int, end_node_id: int,
                               seq: np.ndarray, weight: Optional[np.ndarray],
                               qpos_to_node_id: Optional[np.ndarray],
                               cigar: List[int], read_id: int, tot_read_n: int,
                               inc_both_ends: bool) -> None:
        seq = np.ascontiguousarray(seq, dtype=np.uint8)
        seq_l = len(seq)
        if weight is None:
            weight = np.ones(seq_l, dtype=np.int64)
        weight = np.ascontiguousarray(weight, dtype=np.int64)
        cig = np.asarray(cigar, dtype=np.uint64)
        qpos = None
        qp_ptr = None
        if qpos_to_node_id is not None:
            qpos = np.ascontiguousarray(qpos_to_node_id, dtype=np.int64)
            qp_ptr = _ptr(qpos, ctypes.c_int64)
        add_read_weight = 1 if (abpt.use_qv and abpt.max_n_cons > 1) else 0
        rc = self._lib.apg_add_alignment(
            self._h, beg_node_id, end_node_id,
            _ptr(seq, ctypes.c_uint8), _ptr(weight, ctypes.c_int64), seq_l,
            _ptr(cig, ctypes.c_uint64) if len(cig) else None, len(cig),
            read_id, tot_read_n,
            1 if abpt.use_read_ids else 0, add_read_weight,
            1 if inc_both_ends else 0,
            1 if abpt.wb >= 0 else 0, 1 if abpt.zdrop > 0 else 0,
            qp_ptr)
        if rc != 0:
            raise RuntimeError("native fusion failed")
        if qpos_to_node_id is not None:
            qpos_to_node_id[:seq_l] = qpos[:seq_l]
        self._version += 1

    def add_node(self, base: int) -> int:
        """Graph-building primitive used by incremental-MSA restore
        (io/restore.py; reference src/abpoa_seq.c:608-673)."""
        return int(self._lib.apg_add_node(self._h, int(base)))

    def add_edge(self, from_id: int, to_id: int, check_edge: bool, w: int,
                 add_read_id: bool, add_read_weight: bool, read_id: int,
                 tot_read_n: int) -> None:
        self._lib.apg_add_edge(self._h, int(from_id), int(to_id),
                               1 if check_edge else 0, int(w),
                               1 if add_read_id else 0,
                               1 if add_read_weight else 0, int(read_id),
                               int(tot_read_n))

    def add_aligned_node(self, node_id: int, aligned_id: int) -> None:
        self._lib.apg_add_aligned_node(self._h, int(node_id), int(aligned_id))

    def node_base(self, node_id: int) -> int:
        return int(self._lib.apg_node_base(self._h, int(node_id)))

    def get_aligned_id(self, node_id: int, base: int) -> int:
        return int(self._lib.apg_get_aligned_id(self._h, int(node_id), int(base)))

    def add_alignment(self, abpt: Params, seq, weight, qpos_to_node_id, cigar,
                      read_id: int, tot_read_n: int, inc_both_ends: bool) -> None:
        self.add_subgraph_alignment(abpt, C.SRC_NODE_ID, C.SINK_NODE_ID, seq,
                                    weight, qpos_to_node_id, cigar, read_id,
                                    tot_read_n, inc_both_ends)

    def subgraph_nodes(self, abpt: Params, inc_beg: int, inc_end: int):
        if not self.is_topological_sorted:
            self.topological_sort(abpt)
        out2 = np.zeros(2, dtype=np.int32)
        self._lib.apg_subgraph_nodes(self._h, inc_beg, inc_end,
                                     _ptr(out2, ctypes.c_int32))
        return int(out2[0]), int(out2[1])

    # --------------------------------------------------------- kernel tables
    def build_tables(self, beg_node_id: int, end_node_id: int, banded: bool,
                     bucket_r, bucket_pow2):
        """Returns dict of padded numpy tables for the JAX kernel."""
        lib = self._lib
        maxPO = np.zeros(5, dtype=np.int32)
        none8 = None
        lib.apg_build_tables(self._h, beg_node_id, end_node_id, 0, 0, 0,
                             1 if banded else 0,
                             none8, none8, none8, none8, none8, none8,
                             none8, none8, none8, _ptr(maxPO, ctypes.c_int32))
        maxP, maxO, gn, beg_index, remain_end = [int(x) for x in maxPO]
        R = bucket_r(gn)
        P = bucket_pow2(maxP)
        O = bucket_pow2(maxO)
        base = np.zeros(R, dtype=np.int32)
        row_active = np.zeros(R, dtype=np.uint8)
        pre_idx = np.zeros((R, P), dtype=np.int32)
        pre_msk = np.zeros((R, P), dtype=np.uint8)
        out_idx = np.zeros((R, O), dtype=np.int32)
        out_msk = np.zeros((R, O), dtype=np.uint8)
        remain_rows = np.zeros(R, dtype=np.int32)
        mpl0 = np.zeros(R, dtype=np.int32)
        mpr0 = np.zeros(R, dtype=np.int32)
        lib.apg_build_tables(self._h, beg_node_id, end_node_id, R, P, O,
                             1 if banded else 0,
                             _ptr(base, ctypes.c_int32), _ptr(row_active, ctypes.c_uint8),
                             _ptr(pre_idx, ctypes.c_int32), _ptr(pre_msk, ctypes.c_uint8),
                             _ptr(out_idx, ctypes.c_int32), _ptr(out_msk, ctypes.c_uint8),
                             _ptr(remain_rows, ctypes.c_int32),
                             _ptr(mpl0, ctypes.c_int32), _ptr(mpr0, ctypes.c_int32),
                             _ptr(maxPO, ctypes.c_int32))
        row_active[gn - 1:] = 0
        return dict(base=base, row_active=row_active.astype(bool),
                    pre_idx=pre_idx, pre_msk=pre_msk.astype(bool),
                    out_idx=out_idx, out_msk=out_msk.astype(bool),
                    remain_rows=remain_rows, mpl0=mpl0, mpr0=mpr0,
                    gn=gn, R=R, P=P, O=O, beg_index=beg_index,
                    remain_end=remain_end)

    def write_band(self, beg_index: int, gn: int, mpl: np.ndarray, mpr: np.ndarray):
        mpl = np.ascontiguousarray(mpl, dtype=np.int32)
        mpr = np.ascontiguousarray(mpr, dtype=np.int32)
        self._lib.apg_write_band(self._h, beg_index, gn,
                                 _ptr(mpl, ctypes.c_int32), _ptr(mpr, ctypes.c_int32))

    # --------------------------------------------------------------- export
    def consensus_hb(self):
        """Single-cluster heaviest-bundling consensus computed in C++
        (apg_cons_hb); returns (node_ids, bases, covs) int32 arrays. The
        default `-r0` output path uses this to skip the O(V+E) to_python
        export entirely (it dominated short-read set wall time)."""
        cap = max(16, self.node_n)
        while True:
            ids = np.zeros(cap, dtype=np.int32)
            bases = np.zeros(cap, dtype=np.int32)
            covs = np.zeros(cap, dtype=np.int32)
            n = self._lib.apg_cons_hb(
                self._h, _ptr(ids, ctypes.c_int32),
                _ptr(bases, ctypes.c_int32), _ptr(covs, ctypes.c_int32), cap)
            if n >= 0:
                return ids[:n], bases[:n], covs[:n]
            cap *= 2

    def to_python(self, abpt: Params):
        """Materialize a pure-Python POAGraph for output-time consumers."""
        from ..graph import POAGraph, Node
        lib = self._lib
        counts = np.zeros(6, dtype=np.int64)
        lib.apg_export_sizes(self._h, _ptr(counts, ctypes.c_int64))
        n, tin, tout, tal, trw, tbits = [int(x) for x in counts]
        base = np.zeros(n, dtype=np.uint8)
        n_read = np.zeros(n, dtype=np.int32)
        n_span = np.zeros(n, dtype=np.int32)
        in_off = np.zeros(n + 1, dtype=np.int64)
        in_ids = np.zeros(max(tin, 1), dtype=np.int32)
        in_w = np.zeros(max(tin, 1), dtype=np.int32)
        out_off = np.zeros(n + 1, dtype=np.int64)
        out_ids = np.zeros(max(tout, 1), dtype=np.int32)
        out_w = np.zeros(max(tout, 1), dtype=np.int32)
        al_off = np.zeros(n + 1, dtype=np.int64)
        al_ids = np.zeros(max(tal, 1), dtype=np.int32)
        rw_off = np.zeros(n + 1, dtype=np.int64)
        rw_ids = np.zeros(max(trw, 1), dtype=np.int32)
        rw_vals = np.zeros(max(trw, 1), dtype=np.int32)
        bits = np.zeros(max(tbits, 1), dtype=np.uint64)
        bits_off = np.zeros(max(tout, 1), dtype=np.int64)
        bits_words = np.zeros(max(tout, 1), dtype=np.int64)
        lib.apg_export(self._h, _ptr(base, ctypes.c_uint8),
                       _ptr(n_read, ctypes.c_int32), _ptr(n_span, ctypes.c_int32),
                       _ptr(in_off, ctypes.c_int64), _ptr(in_ids, ctypes.c_int32),
                       _ptr(in_w, ctypes.c_int32),
                       _ptr(out_off, ctypes.c_int64), _ptr(out_ids, ctypes.c_int32),
                       _ptr(out_w, ctypes.c_int32),
                       _ptr(al_off, ctypes.c_int64), _ptr(al_ids, ctypes.c_int32),
                       _ptr(rw_off, ctypes.c_int64), _ptr(rw_ids, ctypes.c_int32),
                       _ptr(rw_vals, ctypes.c_int32),
                       _ptr(bits_off, ctypes.c_int64), _ptr(bits, ctypes.c_uint64),
                       _ptr(bits_words, ctypes.c_int64))
        g = POAGraph()
        g.nodes = []
        # bulk-convert once: ndarray.tolist() is ~30x faster than per-element
        # int() casts, and list slicing below is O(len) C-speed (this export
        # runs once per read set but dominated the small-workload wall)
        base_l = base.tolist()
        n_read_l = n_read.tolist()
        n_span_l = n_span.tolist()
        in_off_l = in_off.tolist()
        in_ids_l = in_ids.tolist()
        in_w_l = in_w.tolist()
        out_off_l = out_off.tolist()
        out_ids_l = out_ids.tolist()
        out_w_l = out_w.tolist()
        al_off_l = al_off.tolist()
        al_ids_l = al_ids.tolist()
        rw_off_l = rw_off.tolist()
        # per-edge read-id bitset words -> arbitrary-precision ints
        words_l = bits_words.tolist()
        boff_l = bits_off.tolist()
        bits_l = bits.tolist()
        read_all = [0] * tout
        for e in range(tout):
            wn = words_l[e]
            if wn == 1:
                read_all[e] = bits_l[boff_l[e]]
            elif wn > 1:
                v = 0
                off = boff_l[e]
                for k in range(wn):
                    v |= bits_l[off + k] << (64 * k)
                read_all[e] = v
        any_rw = trw > 0
        if any_rw:
            rw_ids_l = rw_ids.tolist()
            rw_vals_l = rw_vals.tolist()
        for i in range(n):
            nd = Node(i, base_l[i])
            nd.in_ids = in_ids_l[in_off_l[i]: in_off_l[i + 1]]
            nd.in_w = in_w_l[in_off_l[i]: in_off_l[i + 1]]
            oo, oo2 = out_off_l[i], out_off_l[i + 1]
            nd.out_ids = out_ids_l[oo:oo2]
            nd.out_w = out_w_l[oo:oo2]
            nd.aligned_ids = al_ids_l[al_off_l[i]: al_off_l[i + 1]]
            nd.n_read = n_read_l[i]
            nd.n_span_read = n_span_l[i]
            if any_rw:
                nd.read_weight = dict(zip(rw_ids_l[rw_off_l[i]: rw_off_l[i + 1]],
                                          rw_vals_l[rw_off_l[i]: rw_off_l[i + 1]]))
            nd.read_ids = read_all[oo:oo2]
            g.nodes.append(nd)
        g.is_topological_sorted = self.is_topological_sorted
        if g.is_topological_sorted:
            i2n, n2i = self._index_arrays()
            g.index_to_node_id = i2n.copy()
            g.node_id_to_index = n2i.copy()
            remain = np.zeros(n, dtype=np.int32)
            if self._lib.apg_get_remain(self._h, _ptr(remain, ctypes.c_int32)) == 0:
                g.node_id_to_max_remain = remain
        return g
