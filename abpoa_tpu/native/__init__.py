"""Build/load the native host core (C++ via ctypes; no pybind11 in image)."""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "host_core.cpp")


def _host_tag() -> str:
    """Discriminate the .so cache by host CPU: -march=native binaries must not
    be reused on a machine with a different ISA (SIGILL otherwise)."""
    import hashlib
    import platform
    tag = platform.machine()
    try:
        with open("/proc/cpuinfo") as fp:
            for line in fp:
                if line.startswith(("flags", "Features")):
                    tag += hashlib.sha1(line.encode()).hexdigest()[:8]
                    break
    except OSError:
        pass
    return tag


_LIB = os.path.join(
    _HERE, f"libabpoa_host_{sys.implementation.cache_tag}_{_host_tag()}.so")

_lib = None


def _build() -> None:
    # -march=native unlocks the host's full vector width for the autovectorized
    # DP inner loops (the library is built on demand per host, so this is safe);
    # fall back to the portable baseline if the toolchain rejects it
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB]
    try:
        subprocess.run(base[:2] + ["-march=native"] + base[2:],
                       check=True, capture_output=True)
    except subprocess.CalledProcessError:
        subprocess.run(base, check=True, capture_output=True)


def load():
    """Load (building if needed) the native library; returns None on failure."""
    global _lib
    if _lib is not None:
        return _lib
    try:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_LIB)
    except Exception:
        return None

    c = ctypes
    i32p = c.POINTER(c.c_int32)
    i64p = c.POINTER(c.c_int64)
    u8p = c.POINTER(c.c_uint8)
    u64p = c.POINTER(c.c_uint64)
    lib.apg_create.restype = c.c_void_p
    lib.apg_destroy.argtypes = [c.c_void_p]
    lib.apg_reset.argtypes = [c.c_void_p]
    lib.apg_node_n.argtypes = [c.c_void_p]
    lib.apg_node_n.restype = c.c_int
    lib.apg_is_sorted.argtypes = [c.c_void_p]
    lib.apg_is_sorted.restype = c.c_int
    lib.apg_topological_sort.argtypes = [c.c_void_p, c.c_int, c.c_int]
    lib.apg_add_node.argtypes = [c.c_void_p, c.c_int]
    lib.apg_add_node.restype = c.c_int
    lib.apg_add_edge.argtypes = [c.c_void_p, c.c_int, c.c_int, c.c_int,
                                 c.c_int, c.c_int, c.c_int, c.c_int, c.c_int]
    lib.apg_add_aligned_node.argtypes = [c.c_void_p, c.c_int, c.c_int]
    lib.apg_invalidate_sort.argtypes = [c.c_void_p]
    lib.apg_node_base.argtypes = [c.c_void_p, c.c_int]
    lib.apg_node_base.restype = c.c_int
    lib.apg_get_aligned_id.argtypes = [c.c_void_p, c.c_int, c.c_int]
    lib.apg_get_aligned_id.restype = c.c_int
    lib.apg_add_alignment.argtypes = [
        c.c_void_p, c.c_int, c.c_int, u8p, i64p, c.c_int, u64p, c.c_int,
        c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, i64p]
    lib.apg_add_alignment.restype = c.c_int
    lib.apg_build_tables.argtypes = [
        c.c_void_p, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        i32p, u8p, i32p, u8p, i32p, u8p, i32p, i32p, i32p, i32p]
    lib.apg_build_tables.restype = c.c_int
    lib.apg_write_band.argtypes = [c.c_void_p, c.c_int, c.c_int, i32p, i32p]
    lib.apg_get_index.argtypes = [c.c_void_p, i32p, i32p]
    lib.apg_get_index.restype = c.c_int
    lib.apg_set_msa_rank.argtypes = [c.c_void_p, i32p]
    lib.apg_set_msa_rank.restype = c.c_int
    lib.apg_export_sizes.argtypes = [c.c_void_p, i64p]
    lib.apg_export.argtypes = [
        c.c_void_p, u8p, i32p, i32p, i64p, i32p, i32p, i64p, i32p, i32p,
        i64p, i32p, i64p, i32p, i32p, i64p, u64p, i64p]
    lib.apg_get_remain.argtypes = [c.c_void_p, i32p]
    lib.apg_get_remain.restype = c.c_int
    lib.apg_subgraph_nodes.argtypes = [c.c_void_p, c.c_int, c.c_int, i32p]
    lib.apg_align.argtypes = [
        c.c_void_p, c.c_int, c.c_int, u8p, c.c_int, i32p, i32p,
        u64p, c.c_int, i64p]
    lib.apg_align.restype = c.c_int
    lib.apg_cons_hb.argtypes = [c.c_void_p, i32p, i32p, i32p, c.c_int]
    lib.apg_cons_hb.restype = c.c_int
    _lib = lib
    return lib
