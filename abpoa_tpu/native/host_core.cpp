// Native host core: the mutable POA graph and its per-read hot loop.
//
// The TPU kernel consumes immutable dense snapshots; everything that mutates
// the graph between alignments lives here: cigar fusion (reference semantics:
// /root/reference/src/abpoa_graph.c:689-774), BFS topological sort with
// aligned-group atomicity (:221-266), weight-descending edge sort (:192-219),
// reverse-BFS max_remain (:268-309), and the padded predecessor/out-edge
// tables the JAX kernel gathers through.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
#include <cstdint>
#include <cstring>
#include <cmath>
#include <vector>
#include <algorithm>

namespace {

struct Node {
    uint8_t base = 0;
    std::vector<int32_t> in_ids, in_w;
    std::vector<int32_t> out_ids, out_w;
    std::vector<std::vector<uint64_t>> read_ids;  // bitset words per out edge
    std::vector<int32_t> aligned_ids;
    int32_t n_read = 0;
    int32_t n_span_read = 0;
    std::vector<int32_t> read_weight_ids, read_weight_vals;  // sparse qv weights
};

struct Graph {
    std::vector<Node> nodes;
    std::vector<int32_t> index_to_node_id, node_id_to_index;
    std::vector<int32_t> max_remain, mpl, mpr, msa_rank;
    bool sorted = false;
    bool msa_rank_set = false;
    // edge-sort dirty tracking: the per-read exchange sort is idempotent on
    // nodes whose edge arrays did not change, so re-sorting only the nodes a
    // fusion touched produces byte-identical arrays at a fraction of the
    // O(V * d^2) full pass (it dominated topo time on 100k-node graphs).
    // all_edges_dirty covers resets/restores and any node added before
    // tracking: new nodes mark themselves dirty in add_edge.
    std::vector<uint8_t> edge_dirty;
    bool all_edges_dirty = true;
    // persistent DP workspaces (reused across alignments, like the
    // reference's abpoa_simd_matrix_t); int16 twins back the 16-bit
    // plane-STORAGE mode (math stays int32; stores saturate low), selected
    // per alignment by the reference's score-width bound
    // (abpoa_align_simd.c:1284-1302)
    std::vector<int32_t> wsH, wsE1, wsE2, wsF1, wsF2;
    std::vector<int16_t> wsH16, wsE116, wsE216, wsF116, wsF216;
    std::vector<int32_t> ws_qprof;  // per-alignment query profile (m x qlen+1)
    std::vector<int32_t> ws_pre, ws_pre_off;  // flattened per-row pred lists
    std::vector<int32_t> ws_pre_ps;  // -G path score per pred slot (CSR twin)
    std::vector<uint8_t> ws_index_map;
    std::vector<int32_t> ws_queue, ws_degree;  // BFS scratch (topo sort)
    std::vector<int64_t> ws_row_ptr;
    std::vector<int32_t> ws_beg, ws_end;

    Graph() { reset(); }
    void reset() {
        nodes.clear();
        nodes.resize(2);
        sorted = false;
        msa_rank_set = false;
        edge_dirty.clear();
        all_edges_dirty = true;
    }
    int n() const { return (int)nodes.size(); }
    void mark_edge_dirty(int id) {
        if (all_edges_dirty) return;
        if ((int)edge_dirty.size() <= id) edge_dirty.resize(id + 1, 1);
        else edge_dirty[id] = 1;
    }
};

const int SRC = 0, SINK = 1;
const uint64_t OP_MASK = 0xF;
enum { CMATCH = 0, CINS = 1, CDEL = 2, CDIFF = 3, CSOFT = 4, CHARD = 5 };

int add_node(Graph& g, uint8_t base) {
    g.nodes.emplace_back();
    g.nodes.back().base = base;
    return g.n() - 1;
}

void set_read_weight(Node& node, int read_id, int w) {
    for (size_t i = 0; i < node.read_weight_ids.size(); ++i)
        if (node.read_weight_ids[i] == read_id) { node.read_weight_vals[i] = w; return; }
    node.read_weight_ids.push_back(read_id);
    node.read_weight_vals.push_back(w);
}

void add_edge(Graph& g, int from_id, int to_id, bool check_edge, int w,
              bool add_read_id, bool add_read_weight, int read_id,
              int read_ids_n) {
    g.mark_edge_dirty(from_id);
    g.mark_edge_dirty(to_id);
    Node& fr = g.nodes[from_id];
    Node& to = g.nodes[to_id];
    int out_edge_i = -1;
    if (check_edge) {
        for (size_t i = 0; i < to.in_ids.size(); ++i)
            if (to.in_ids[i] == from_id) { to.in_w[i] += w; break; }
        for (size_t i = 0; i < fr.out_ids.size(); ++i)
            if (fr.out_ids[i] == to_id) { fr.out_w[i] += w; out_edge_i = (int)i; break; }
    }
    if (out_edge_i < 0) {
        to.in_ids.push_back(from_id);
        to.in_w.push_back(w);
        fr.out_ids.push_back(to_id);
        fr.out_w.push_back(w);
        fr.read_ids.emplace_back();
        out_edge_i = (int)fr.out_ids.size() - 1;
    }
    if (add_read_id) {
        auto& bits = fr.read_ids[out_edge_i];
        if ((int)bits.size() < read_ids_n) bits.resize(read_ids_n, 0);
        bits[read_id >> 6] |= 1ULL << (read_id & 63);
    }
    fr.n_read += 1;
    if (add_read_weight) set_read_weight(fr, read_id, w);
}

int get_aligned_id(Graph& g, int node_id, uint8_t base) {
    for (int aid : g.nodes[node_id].aligned_ids)
        if (g.nodes[aid].base == base) return aid;
    return -1;
}

void add_aligned_node(Graph& g, int node_id, int aligned_id) {
    Node& node = g.nodes[node_id];
    for (int ex : node.aligned_ids) {
        g.nodes[ex].aligned_ids.push_back(aligned_id);
        g.nodes[aligned_id].aligned_ids.push_back(ex);
    }
    node.aligned_ids.push_back(aligned_id);
    g.nodes[aligned_id].aligned_ids.push_back(node_id);
}

// exact replication of the reference's exchange sort (ties depend on it)
void sort_node_edges(Node& node) {
    int n = (int)node.in_ids.size();
    for (int j = 0; j < n - 1; ++j)
        for (int k = j + 1; k < n; ++k)
            if (node.in_w[j] < node.in_w[k]) {
                std::swap(node.in_ids[j], node.in_ids[k]);
                std::swap(node.in_w[j], node.in_w[k]);
            }
    n = (int)node.out_ids.size();
    for (int j = 0; j < n - 1; ++j)
        for (int k = j + 1; k < n; ++k)
            if (node.out_w[j] < node.out_w[k]) {
                std::swap(node.out_ids[j], node.out_ids[k]);
                std::swap(node.out_w[j], node.out_w[k]);
                std::swap(node.read_ids[j], node.read_ids[k]);
            }
}

void sort_in_out_ids(Graph& g) {
    if (!g.all_edges_dirty) {
        const int lim = std::min((int)g.edge_dirty.size(), g.n());
        for (int i = 0; i < lim; ++i)
            if (g.edge_dirty[i]) {
                sort_node_edges(g.nodes[i]);
                g.edge_dirty[i] = 0;
            }
        // nodes beyond edge_dirty.size() were never touched since tracking
        // began (mark_edge_dirty extends the vector on first touch)
        return;
    }
    for (auto& node : g.nodes) sort_node_edges(node);
    g.edge_dirty.assign(g.n(), 0);
    g.all_edges_dirty = false;
}

bool bfs_set_node_index(Graph& g) {
    // flat FIFO over a persistent workspace (identical order to the former
    // std::deque; every node is enqueued at most once so n slots suffice)
    int n = g.n();
    g.index_to_node_id.assign(n, 0);
    g.node_id_to_index.assign(n, 0);
    std::vector<int32_t>& in_degree = g.ws_degree;
    in_degree.resize(n);
    for (int i = 0; i < n; ++i) in_degree[i] = (int)g.nodes[i].in_ids.size();
    std::vector<int32_t>& q = g.ws_queue;
    if ((int)q.size() < n) q.resize(n);
    int head = 0, tail = 0;
    q[tail++] = SRC;
    int index = 0;
    while (head < tail) {
        int cur = q[head++];
        g.index_to_node_id[index] = cur;
        g.node_id_to_index[cur] = index++;
        if (cur == SINK) return true;
        for (int out_id : g.nodes[cur].out_ids) {
            if (--in_degree[out_id] == 0) {
                bool ok = true;
                for (int a : g.nodes[out_id].aligned_ids)
                    if (in_degree[a] != 0) { ok = false; break; }
                if (!ok) continue;
                q[tail++] = out_id;
                for (int a : g.nodes[out_id].aligned_ids) q[tail++] = a;
            }
        }
    }
    return false;
}

bool bfs_set_node_remain(Graph& g) {
    int n = g.n();
    g.max_remain.assign(n, 0);
    std::vector<int32_t>& out_degree = g.ws_degree;
    out_degree.resize(n);
    for (int i = 0; i < n; ++i) out_degree[i] = (int)g.nodes[i].out_ids.size();
    std::vector<int32_t>& q = g.ws_queue;
    if ((int)q.size() < n) q.resize(n);
    int head = 0, tail = 0;
    q[tail++] = SINK;
    g.max_remain[SINK] = -1;
    while (head < tail) {
        int cur = q[head++];
        Node& node = g.nodes[cur];
        if (cur != SINK) {
            int max_w = -1, max_id = -1;
            for (size_t i = 0; i < node.out_ids.size(); ++i)
                if (node.out_w[i] > max_w) { max_w = node.out_w[i]; max_id = node.out_ids[i]; }
            g.max_remain[cur] = g.max_remain[max_id] + 1;
        }
        if (cur == SRC) return true;
        for (int in_id : node.in_ids)
            if (--out_degree[in_id] == 0) q[tail++] = in_id;
    }
    return false;
}

void topological_sort(Graph& g, bool banded, bool zdrop) {
    bfs_set_node_index(g);
    sort_in_out_ids(g);
    if (banded) {
        int n = g.n();
        g.mpr.assign(n, 0);
        g.mpl.assign(n, n);
        bfs_set_node_remain(g);
    } else if (zdrop) {
        bfs_set_node_remain(g);
    }
    g.sorted = true;
    g.msa_rank_set = false;
}

void update_n_span(Graph& g, int beg_id, int end_id, bool inc_both_ends) {
    int src_index = g.node_id_to_index[beg_id];
    int sink_index = g.node_id_to_index[end_id];
    for (int i = src_index + 1; i < sink_index; ++i)
        g.nodes[g.index_to_node_id[i]].n_span_read += 1;
    if (inc_both_ends) {
        g.nodes[beg_id].n_span_read += 1;
        g.nodes[end_id].n_span_read += 1;
    }
}

}  // namespace

extern "C" {

void* apg_create() { return new Graph(); }
void apg_destroy(void* h) { delete (Graph*)h; }
void apg_reset(void* h) { ((Graph*)h)->reset(); }
int apg_node_n(void* h) { return ((Graph*)h)->n(); }
void apg_invalidate_sort(void* h) { ((Graph*)h)->sorted = false; }
int apg_is_sorted(void* h) { return ((Graph*)h)->sorted ? 1 : 0; }

void apg_topological_sort(void* h, int banded, int zdrop) {
    topological_sort(*(Graph*)h, banded != 0, zdrop != 0);
}

// graph-building primitives for incremental-MSA restore (reference
// abpoa_restore_graph path, src/abpoa_seq.c:608-673)
int apg_add_node(void* h, int base) {
    Graph& g = *(Graph*)h;
    g.sorted = false;
    return add_node(g, (uint8_t)base);
}

void apg_add_edge(void* h, int from_id, int to_id, int check_edge, int w,
                  int add_read_id, int add_read_weight, int read_id,
                  int tot_read_n) {
    Graph& g = *(Graph*)h;
    g.sorted = false;
    int read_ids_n = tot_read_n > 0 ? 1 + ((tot_read_n - 1) >> 6) : 1;
    add_edge(g, from_id, to_id, check_edge != 0, w, add_read_id != 0,
             add_read_weight != 0, read_id, read_ids_n);
}

void apg_add_aligned_node(void* h, int node_id, int aligned_id) {
    add_aligned_node(*(Graph*)h, node_id, aligned_id);
}

int apg_node_base(void* h, int node_id) {
    return ((Graph*)h)->nodes[node_id].base;
}

int apg_get_aligned_id(void* h, int node_id, int base) {
    return get_aligned_id(*(Graph*)h, node_id, (uint8_t)base);
}

// Fuse one alignment (or seed an empty graph). Returns 0 on success.
int apg_add_alignment(void* h, int beg_node_id, int end_node_id,
                      const uint8_t* seq, const int64_t* weight, int seq_l,
                      const uint64_t* cigar, int n_cigar,
                      int read_id, int tot_read_n,
                      int use_read_ids, int add_read_weight, int inc_both_ends,
                      int banded, int zdrop,
                      int64_t* qpos_to_node_id) {
    Graph& g = *(Graph*)h;
    int read_ids_n = 1 + ((tot_read_n - 1) >> 6);
    bool arid = use_read_ids != 0, arw = add_read_weight != 0;
    if (g.n() == 2) {  // empty graph: seed a chain (abpoa_graph.c:573-593)
        if (seq_l <= 0) return 0;
        int last_id = SRC;
        for (int i = 0; i < seq_l; ++i) {
            int cur = add_node(g, seq[i]);
            if (qpos_to_node_id) qpos_to_node_id[i] = cur;
            add_edge(g, last_id, cur, false, (int)weight[i], arid, arw, read_id, read_ids_n);
            g.nodes[cur].n_span_read = g.nodes[last_id].n_span_read;
            last_id = cur;
        }
        add_edge(g, last_id, SINK, false, (int)weight[seq_l - 1], arid, arw, read_id, read_ids_n);
        topological_sort(g, banded != 0, zdrop != 0);
        update_n_span(g, SRC, SINK, true);
        return 0;
    }
    if (n_cigar == 0) return 0;
    int query_id = -1;
    bool last_new = false;
    int last_id = beg_node_id;
    for (int c = 0; c < n_cigar; ++c) {
        uint64_t p = cigar[c];
        int op = (int)(p & OP_MASK);
        if (op == CMATCH) {
            int node_id = (int)((p >> 34) & 0x3FFFFFFF);
            query_id++;
            uint8_t b = seq[query_id];
            bool add = (last_id != beg_node_id) || inc_both_ends;
            if (g.nodes[node_id].base != b) {  // mismatch
                int aligned_id = get_aligned_id(g, node_id, b);
                if (aligned_id != -1) {
                    add_edge(g, last_id, aligned_id, !last_new, (int)weight[query_id],
                             arid && add, arw, read_id, read_ids_n);
                    if (!add) g.nodes[last_id].n_read--;
                    last_id = aligned_id;
                    last_new = false;
                } else {
                    int new_id = add_node(g, b);
                    add_edge(g, last_id, new_id, false, (int)weight[query_id],
                             arid && add, arw, read_id, read_ids_n);
                    g.nodes[new_id].n_span_read = g.nodes[last_id].n_span_read;
                    if (!add) g.nodes[last_id].n_read--;
                    last_id = new_id;
                    last_new = true;
                    add_aligned_node(g, node_id, new_id);
                }
            } else {  // match
                add_edge(g, last_id, node_id, !last_new, (int)weight[query_id],
                         arid && add, arw, read_id, read_ids_n);
                if (!add) g.nodes[last_id].n_read--;
                last_id = node_id;
                last_new = false;
            }
            if (qpos_to_node_id) qpos_to_node_id[query_id] = last_id;
        } else if (op == CINS || op == CSOFT || op == CHARD) {
            int len = (int)((p >> 4) & 0x3FFFFFFF);
            query_id += len;
            for (int j = len - 1; j >= 0; --j) {
                int new_id = add_node(g, seq[query_id - j]);
                bool add = (last_id != beg_node_id) || inc_both_ends;
                add_edge(g, last_id, new_id, false, (int)weight[query_id - j],
                         arid && add, arw, read_id, read_ids_n);
                g.nodes[new_id].n_span_read = g.nodes[last_id].n_span_read;
                if (!add) g.nodes[last_id].n_read--;
                last_id = new_id;
                last_new = true;
                if (qpos_to_node_id) qpos_to_node_id[query_id - j] = last_id;
            }
        }  // CDEL: skip
    }
    add_edge(g, last_id, end_node_id, !last_new, (int)weight[seq_l - 1],
             arid, arw, read_id, read_ids_n);
    topological_sort(g, banded != 0, zdrop != 0);
    update_n_span(g, beg_node_id, end_node_id, inc_both_ends != 0);
    return 0;
}

// ----- kernel snapshot ------------------------------------------------------
// Build the BFS-reachable subgraph mask + padded pre/out tables for the dp
// window [beg_index, end_index]. Two-phase: pass P=O=0 to query max degrees.
int apg_build_tables(void* h, int beg_node_id, int end_node_id,
                     int R, int P, int O, int banded,
                     int32_t* base, uint8_t* row_active,
                     int32_t* pre_idx, uint8_t* pre_msk,
                     int32_t* out_idx, uint8_t* out_msk,
                     int32_t* remain_rows, int32_t* mpl0, int32_t* mpr0,
                     int32_t* maxPO /*out: [maxP, maxO, gn, beg_index, remain_end]*/) {
    Graph& g = *(Graph*)h;
    int beg_index = g.node_id_to_index[beg_node_id];
    int end_index = g.node_id_to_index[end_node_id];
    int gn = end_index - beg_index + 1;
    std::vector<uint8_t> index_map(g.n(), 0);
    index_map[beg_index] = index_map[end_index] = 1;
    for (int i = beg_index; i < end_index - 1; ++i) {
        if (!index_map[i]) continue;
        int nid = g.index_to_node_id[i];
        for (int out_id : g.nodes[nid].out_ids)
            index_map[g.node_id_to_index[out_id]] = 1;
    }
    int maxP = 1, maxO = 1;
    if (banded) {
        // first-row band seeding (abpoa_align_simd.c:617-626)
        g.mpl[beg_node_id] = g.mpr[beg_node_id] = 0;
        for (int out_id : g.nodes[beg_node_id].out_ids)
            if (index_map[g.node_id_to_index[out_id]])
                g.mpl[out_id] = g.mpr[out_id] = 1;
    }
    for (int i = 0; i < gn; ++i) {
        int nid = g.index_to_node_id[beg_index + i];
        bool active = index_map[beg_index + i] != 0;
        if (P > 0) {
            base[i] = g.nodes[nid].base;
            row_active[i] = active && i > 0 ? 1 : 0;
            if (banded) {
                remain_rows[i] = g.max_remain[nid];
                mpl0[i] = g.mpl[nid];
                mpr0[i] = g.mpr[nid];
            }
        }
        if (i == 0 || !active) continue;
        int np = 0;
        for (int in_id : g.nodes[nid].in_ids) {
            int p_idx = g.node_id_to_index[in_id];
            if (index_map[p_idx]) {
                if (P > 0) {
                    pre_idx[(int64_t)i * P + np] = p_idx - beg_index;
                    pre_msk[(int64_t)i * P + np] = 1;
                }
                np++;
            }
        }
        maxP = std::max(maxP, np);
        if (banded && i < gn - 1) {
            int no = 0;
            for (int out_id : g.nodes[nid].out_ids) {
                if (P > 0) {
                    out_idx[(int64_t)i * O + no] = g.node_id_to_index[out_id] - beg_index;
                    out_msk[(int64_t)i * O + no] = 1;
                }
                no++;
            }
            maxO = std::max(maxO, no);
        }
    }
    maxPO[0] = maxP;
    maxPO[1] = maxO;
    maxPO[2] = gn;
    maxPO[3] = beg_index;
    maxPO[4] = banded ? g.max_remain[end_node_id] : 0;
    return 0;
}

void apg_write_band(void* h, int beg_index, int gn, const int32_t* mpl, const int32_t* mpr) {
    Graph& g = *(Graph*)h;
    for (int i = 0; i < gn; ++i) {
        int nid = g.index_to_node_id[beg_index + i];
        g.mpl[nid] = mpl[i];
        g.mpr[nid] = mpr[i];
    }
}

int apg_get_index(void* h, int32_t* index_to_node_id, int32_t* node_id_to_index) {
    Graph& g = *(Graph*)h;
    std::memcpy(index_to_node_id, g.index_to_node_id.data(), g.n() * 4);
    std::memcpy(node_id_to_index, g.node_id_to_index.data(), g.n() * 4);
    return g.n();
}

// DFS msa rank (abpoa_graph.c:359-419); returns msa_len (rank[sink]-1)
int apg_set_msa_rank(void* h, int32_t* rank_out) {
    Graph& g = *(Graph*)h;
    int n = g.n();
    g.msa_rank.assign(n, 0);
    std::vector<int32_t> in_degree(n);
    for (int i = 0; i < n; ++i) in_degree[i] = (int)g.nodes[i].in_ids.size();
    std::vector<int> stack{SRC};
    g.msa_rank[SRC] = -1;
    int msa_rank = 0;
    while (!stack.empty()) {
        int cur = stack.back(); stack.pop_back();
        if (g.msa_rank[cur] < 0) {
            g.msa_rank[cur] = msa_rank;
            for (int a : g.nodes[cur].aligned_ids) g.msa_rank[a] = msa_rank;
            msa_rank++;
        }
        if (cur == SINK) {
            g.msa_rank_set = true;
            if (rank_out) std::memcpy(rank_out, g.msa_rank.data(), n * 4);
            return g.msa_rank[SINK] - 1;
        }
        for (int out_id : g.nodes[cur].out_ids) {
            if (--in_degree[out_id] == 0) {
                bool ok = true;
                for (int a : g.nodes[out_id].aligned_ids)
                    if (in_degree[a] != 0) { ok = false; break; }
                if (!ok) continue;
                stack.push_back(out_id);
                g.msa_rank[out_id] = -1;
                for (int a : g.nodes[out_id].aligned_ids) {
                    stack.push_back(a);
                    g.msa_rank[a] = -1;
                }
            }
        }
    }
    return -1;
}

// ----- full export (for consensus / MSA / GFA writers on the Python side) ---
// sizes query: fills counts[0..3] = [node_n, tot_in_edges, tot_out_edges,
// tot_aligned, tot_read_weight, read_ids_words_per_edge_total]
int apg_export_sizes(void* h, int64_t* counts) {
    Graph& g = *(Graph*)h;
    int64_t tin = 0, tout = 0, tal = 0, trw = 0, tbits = 0;
    for (auto& node : g.nodes) {
        tin += node.in_ids.size();
        tout += node.out_ids.size();
        tal += node.aligned_ids.size();
        trw += node.read_weight_ids.size();
        for (auto& b : node.read_ids) tbits += b.size();
    }
    counts[0] = g.n(); counts[1] = tin; counts[2] = tout; counts[3] = tal;
    counts[4] = trw; counts[5] = tbits;
    return 0;
}

int apg_export(void* h,
               uint8_t* base, int32_t* n_read, int32_t* n_span,
               int64_t* in_off, int32_t* in_ids, int32_t* in_w,
               int64_t* out_off, int32_t* out_ids, int32_t* out_w,
               int64_t* al_off, int32_t* al_ids,
               int64_t* rw_off, int32_t* rw_ids, int32_t* rw_vals,
               int64_t* bits_off, uint64_t* bits /* per out edge, CSR by words */,
               int64_t* bits_words /* per out edge word count */) {
    Graph& g = *(Graph*)h;
    int64_t iin = 0, iout = 0, ial = 0, irw = 0, ibits = 0, iedge = 0;
    for (int i = 0; i < g.n(); ++i) {
        Node& node = g.nodes[i];
        base[i] = node.base;
        n_read[i] = node.n_read;
        n_span[i] = node.n_span_read;
        in_off[i] = iin;
        for (size_t j = 0; j < node.in_ids.size(); ++j) {
            in_ids[iin] = node.in_ids[j];
            in_w[iin++] = node.in_w[j];
        }
        out_off[i] = iout;
        for (size_t j = 0; j < node.out_ids.size(); ++j) {
            out_ids[iout] = node.out_ids[j];
            out_w[iout++] = node.out_w[j];
            bits_words[iedge] = (int64_t)node.read_ids[j].size();
            bits_off[iedge++] = ibits;
            for (uint64_t wd : node.read_ids[j]) bits[ibits++] = wd;
        }
        al_off[i] = ial;
        for (int a : node.aligned_ids) al_ids[ial++] = a;
        rw_off[i] = irw;
        for (size_t j = 0; j < node.read_weight_ids.size(); ++j) {
            rw_ids[irw] = node.read_weight_ids[j];
            rw_vals[irw++] = node.read_weight_vals[j];
        }
    }
    in_off[g.n()] = iin; out_off[g.n()] = iout; al_off[g.n()] = ial; rw_off[g.n()] = irw;
    return 0;
}

int apg_get_remain(void* h, int32_t* remain) {
    Graph& g = *(Graph*)h;
    if (g.max_remain.empty()) return -1;
    std::memcpy(remain, g.max_remain.data(), g.n() * 4);
    return 0;
}

// -G log-scaled path score for in-edge `in_pos` of `nid`
// (reference abpoa_graph.c:429-437; C round() = half away from zero)
static int32_t incre_path_score(Graph& g, int nid, int in_pos) {
    int pre_id = g.nodes[nid].in_ids[in_pos];
    const Node& pre = g.nodes[pre_id];
    int64_t node_w = 0;
    for (int32_t w : pre.out_w) node_w += w;
    int64_t edge_w = g.nodes[nid].in_w[in_pos];
    if (node_w == 0 || edge_w == 0) return 0;
    double v = std::log((double)edge_w / (double)node_w);
    int32_t s = (int32_t)(v >= 0 ? std::floor(v + 0.5) : std::ceil(v - 0.5));
    return std::max(s, (int32_t)-20);
}

// subgraph closure expansion (abpoa_graph.c:595-678)
static bool is_full_upstream(Graph& g, int up, int down, int beg, int end) {
    int mn = std::min(up, beg), mx = std::max(down, end);
    for (int i = up + 1; i <= down; ++i) {
        int nid = g.index_to_node_id[i];
        for (int in_id : g.nodes[nid].in_ids) {
            int idx = g.node_id_to_index[in_id];
            if (idx < mn || idx > mx) return false;
        }
    }
    return true;
}

int apg_subgraph_nodes(void* h, int inc_beg, int inc_end, int32_t* out2) {
    Graph& g = *(Graph*)h;
    int beg_index = g.node_id_to_index[inc_beg];
    int end_index = g.node_id_to_index[inc_end];
    int b = beg_index, e = end_index;
    while (true) {
        int mn = b;
        for (int i = b; i <= e; ++i) {
            int nid = g.index_to_node_id[i];
            for (int in_id : g.nodes[nid].in_ids)
                mn = std::min(mn, (int)g.node_id_to_index[in_id]);
        }
        if (is_full_upstream(g, mn, b, b, e)) { b = mn; break; }
        e = b; b = mn;
    }
    int b2 = beg_index, e2 = end_index;
    while (true) {
        int mx = e2;
        for (int i = b2; i <= e2; ++i) {
            int nid = g.index_to_node_id[i];
            for (int out_id : g.nodes[nid].out_ids)
                mx = std::max(mx, (int)g.node_id_to_index[out_id]);
        }
        if (is_full_upstream(g, e2, mx, b2, e2)) { e2 = mx; break; }
        b2 = e2; e2 = mx;
    }
    out2[0] = g.index_to_node_id[b];
    out2[1] = g.index_to_node_id[e2];
    return 0;
}

}  // extern "C"

// ===========================================================================
// Native scalar DP kernel: adaptive-banded sequence-to-(sub)graph alignment.
//
// Same semantics as the Python/NumPy oracle (abpoa_tpu/align/oracle.py, the
// golden-verified readable spec of the reference's SIMD kernel): banded
// storage (one contiguous buffer, per-row offsets), int32 scores, sequential
// F gap chains, reference backtrack op priority and tie-breaks. Serves as the
// fast host fallback when no accelerator is reachable, and as the CPU side of
// the anchored-window pipeline.
// ===========================================================================

namespace {

const int32_t KINT32_MIN = INT32_MIN;
const int32_t KINT16_MIN = INT16_MIN;

// int16 plane storage: all DP arithmetic stays int32 (values are bounded by
// the width-selection check below); only the PLANE arrays narrow, halving
// the bandwidth that dominates the row loop. Stores saturate at INT16_MIN —
// decayed -inf chains clamp instead of wrapping (the reference's saturating
// SIMD subs give the same guarantee, simd_instruction.h) — and saturated
// cells stay far below every reachable real score, so backtrack equalities
// on the optimal path are unaffected.
template <typename T> inline T clamp_store(int32_t v) { return (T)v; }
template <> inline int16_t clamp_store<int16_t>(int32_t v) {
    return (int16_t)std::max(v, (int32_t)INT16_MIN);
}

template <typename T> struct PlaneWS;
template <> struct PlaneWS<int32_t> {
    static std::vector<int32_t>& H(Graph& g) { return g.wsH; }
    static std::vector<int32_t>& E1(Graph& g) { return g.wsE1; }
    static std::vector<int32_t>& E2(Graph& g) { return g.wsE2; }
    static std::vector<int32_t>& F1(Graph& g) { return g.wsF1; }
    static std::vector<int32_t>& F2(Graph& g) { return g.wsF2; }
};
template <> struct PlaneWS<int16_t> {
    static std::vector<int16_t>& H(Graph& g) { return g.wsH16; }
    static std::vector<int16_t>& E1(Graph& g) { return g.wsE116; }
    static std::vector<int16_t>& E2(Graph& g) { return g.wsE216; }
    static std::vector<int16_t>& F1(Graph& g) { return g.wsF116; }
    static std::vector<int16_t>& F2(Graph& g) { return g.wsF216; }
};

template <typename T>
struct DpPlanes {
    // banded rows: row i occupies [row_ptr[i], row_ptr[i] + width_i)
    // views over the graph's persistent workspaces (no per-call allocation)
    std::vector<int64_t>& row_ptr;
    std::vector<int32_t>& beg;
    std::vector<int32_t>& end;
    std::vector<T>& H;
    std::vector<T>& E1;
    std::vector<T>& E2;
    std::vector<T>& F1;
    std::vector<T>& F2;
    int64_t used = 0;
    int32_t inf = 0;
    int n_planes = 5;

    explicit DpPlanes(Graph& g)
        : row_ptr(g.ws_row_ptr), beg(g.ws_beg), end(g.ws_end),
          H(PlaneWS<T>::H(g)), E1(PlaneWS<T>::E1(g)), E2(PlaneWS<T>::E2(g)),
          F1(PlaneWS<T>::F1(g)), F2(PlaneWS<T>::F2(g)) {}

    void start(int gn, int np) {
        n_planes = np;
        used = 0;
        if ((int)row_ptr.size() < gn + 1) {
            row_ptr.resize(gn + 1);
            beg.resize(gn);
            end.resize(gn);
        }
        std::fill(beg.begin(), beg.begin() + gn, 0);
        std::fill(end.begin(), end.begin() + gn, -1);
    }
    void append_row(int i, int b, int e) {
        beg[i] = b;
        end[i] = e;
        row_ptr[i] = used;
        used += e - b + 1;
        if ((int64_t)H.size() < used) {
            int64_t cap = std::max<int64_t>(used, (int64_t)H.size() * 2);
            H.resize(cap);
            if (n_planes >= 3) { E1.resize(cap); F1.resize(cap); }
            if (n_planes >= 5) { E2.resize(cap); F2.resize(cap); }
        }
    }

    inline int32_t get(const std::vector<T>& P, int i, int j) const {
        if (j < beg[i] || j > end[i]) return inf;
        return (int32_t)P[row_ptr[i] + (j - beg[i])];
    }
    inline int32_t h(int i, int j) const { return get(H, i, j); }
    inline int32_t e1(int i, int j) const { return get(E1, i, j); }
    inline int32_t e2(int i, int j) const { return get(E2, i, j); }
    inline int32_t f1(int i, int j) const { return get(F1, i, j); }
    inline int32_t f2(int i, int j) const { return get(F2, i, j); }
};

struct CigBuf {
    uint64_t* out;
    int cap, n = 0;
    bool overflow = false;
    void push(int op, int len, int64_t node_id, int64_t query_id) {
        // packed-cigar push with INS-run merging (abpoa_align.h:54-73)
        if (n > 0 && (op == 1 || op == 4 || op == 5) && (int)(out[n - 1] & 0xF) == op) {
            out[n - 1] += (uint64_t)len << 4;
            return;
        }
        if (n >= cap) { overflow = true; return; }
        uint64_t v;
        if (op == 0 || op == 3) v = (uint64_t)(node_id & 0x3FFFFFFF) << 34 |
                                     (uint64_t)(query_id & 0x3FFFFFFF) << 4 | op;
        else if (op == 2) v = (uint64_t)(node_id & 0x3FFFFFFF) << 34 |
                              (uint64_t)(len & 0x3FFFFFFF) << 4 | op;
        else v = (uint64_t)(query_id & 0x3FFFFFFF) << 34 |
                 (uint64_t)(len & 0x3FFFFFFF) << 4 | op;
        out[n++] = v;
    }
};

}  // namespace

template <typename T>
int apg_align_core(void* h, int beg_node_id, int end_node_id,
                   const uint8_t* query, int qlen, const int32_t* mat,
                   const int32_t* params, uint64_t* cigar_out, int cigar_cap,
                   int64_t* meta);

extern "C" {

// params layout (int32): [align_mode, gap_mode, wb, wf_x1e6, zdrop, m,
//                         o1, e1, o2, e2, min_mis, put_gap_on_right,
//                         put_gap_at_end, ret_cigar, inc_path_score,
//                         max_mat, force_int32_planes]
// meta out (int64): [best_score, node_s, node_e, query_s, query_e,
//                    n_aln_bases, n_matched_bases, n_cigar]
int apg_align(void* h, int beg_node_id, int end_node_id,
              const uint8_t* query, int qlen, const int32_t* mat,
              const int32_t* params, uint64_t* cigar_out, int cigar_cap,
              int64_t* meta) {
    // score-width selection (reference simd_abpoa_align_sequence_to_subgraph,
    // abpoa_align_simd.c:1284-1302): int16 plane STORAGE while the worst-case
    // score bound fits, int32 after. Both widths produce identical output —
    // the bound guarantees every reachable value fits int16, and saturated
    // -inf cells stay below every real score.
    Graph& g = *(Graph*)h;
    const int32_t o1 = params[6], e1 = params[7];
    const int32_t e2 = params[9];
    const int32_t oe1 = o1 + e1, oe2 = params[8] + e2;
    const int32_t min_mis = params[10];
    const int32_t max_mat = params[15];
    const bool force32 = params[16] != 0;
    const int beg_index = g.node_id_to_index[beg_node_id];
    const int end_index = g.node_id_to_index[end_node_id];
    const int32_t gn = end_index - beg_index + 1;
    const int32_t ln = std::max((int32_t)qlen, gn);
    const int64_t bound = std::max((int64_t)qlen * max_mat,
                                   (int64_t)ln * e1 + o1);
    // the int16 inf sentinel is INT16_MIN + max(min_mis, oe1, oe2) +
    // 512*max(e1,e2) (underflow headroom, apg_align_core); the limit must
    // leave that same headroom below the most negative reachable score or
    // inf could rise into — or above — the valid range (large extension
    // penalties then simply select int32)
    const int64_t limit = 32767 - min_mis - oe1 - oe2
        - 512 * (int64_t)std::max(e1, e2);
    // -G accumulates per-transition path scores (incre_path_score, up to
    // -20 each) on top of the alignment score; the static bound above only
    // models match/gap growth, so long -G alignments can sink past the
    // int16 inf sentinel and wrap. Always take the int32 core under -G.
    const bool inc_ps = params[14] != 0;
    if (!force32 && !inc_ps && bound <= limit)
        return apg_align_core<int16_t>(h, beg_node_id, end_node_id, query,
                                       qlen, mat, params, cigar_out,
                                       cigar_cap, meta);
    return apg_align_core<int32_t>(h, beg_node_id, end_node_id, query, qlen,
                                   mat, params, cigar_out, cigar_cap, meta);
}


int apg_cons_hb(void* h, int32_t* ids_out, int32_t* base_out,
                int32_t* cov_out, int cap) {
    // Heaviest-bundling consensus, single cluster / read-count weights (the
    // default -r0 config): reverse BFS from sink, per-node argmax out-edge
    // weight with path-score tiebreak, then walk max_out from source
    // (reference abpoa_heaviest_bundling src/abpoa_output.c:478-548, walk
    // :376-392). Multi-cluster / qv-weighted calls stay on the Python side
    // (they need per-edge read-id bitsets).
    Graph& g = *(Graph*)h;
    const int n = g.n();
    if (n <= 2) return 0;
    const int src = 0, sink = 1;
    // int64 scores: the Python path accumulates in unbounded ints, and a
    // qv-weighted long-path sum can exceed int32
    std::vector<int64_t> score(n, 0);
    std::vector<int32_t> max_out(n, -1), out_deg(n);
    for (int i = 0; i < n; ++i) out_deg[i] = (int)g.nodes[i].out_ids.size();
    std::vector<int32_t>& q = g.ws_queue;
    if ((int)q.size() < n) q.resize(n);
    int head = 0, tail = 0;
    q[tail++] = sink;
    while (head < tail) {
        const int cur = q[head++];
        const Node& node = g.nodes[cur];
        if (cur == sink) {
            score[cur] = 0;
        } else if (cur == src) {
            int64_t path_score = -1;
            int32_t path_max_w = -1;
            int max_id = -1;
            for (size_t i = 0; i < node.out_ids.size(); ++i) {
                const int out_id = node.out_ids[i];
                const int32_t out_w = node.out_w[i];
                if (out_w > path_max_w
                        || (out_w == path_max_w && score[out_id] > path_score)) {
                    max_id = out_id;
                    path_score = score[out_id];
                    path_max_w = out_w;
                }
            }
            max_out[cur] = max_id;
            break;
        } else {
            // seed from the first edge, not an INT32_MIN sentinel: the
            // sentinel path could tie (max_w == out_w) while max_id is
            // still -1 and read score[-1] (UB)
            int max_id = node.out_ids[0];
            int32_t max_w = node.out_w[0];
            for (size_t i = 1; i < node.out_ids.size(); ++i) {
                const int out_id = node.out_ids[i];
                const int32_t out_w = node.out_w[i];
                if (max_w < out_w) {
                    max_w = out_w;
                    max_id = out_id;
                } else if (max_w == out_w && score[max_id] <= score[out_id]) {
                    max_id = out_id;
                }
            }
            score[cur] = max_w + score[max_id];
            max_out[cur] = max_id;
        }
        for (int in_id : node.in_ids)
            if (--out_deg[in_id] == 0) q[tail++] = in_id;
    }
    // a graph whose source never reached the BFS (dead-end component) or
    // whose source has no out edges has no src->sink chain: walking from
    // max_out[src] == -1 would index max_out[-1] (UB)
    if (max_out[src] < 0) return 0;
    int len = 0;
    for (int cur = max_out[src]; cur != sink && cur >= 0; cur = max_out[cur]) {
        if (len >= cap) return -1;  // caller resizes and retries
        ids_out[len] = cur;
        base_out[len] = g.nodes[cur].base;
        cov_out[len] = g.nodes[cur].n_read;
        ++len;
    }
    return len;
}


}  // extern "C"

// templates cannot carry C linkage; apg_align above is the C-ABI dispatcher
template <typename T>
int apg_align_core(void* h, int beg_node_id, int end_node_id,
                   const uint8_t* query, int qlen, const int32_t* mat,
                   const int32_t* params, uint64_t* cigar_out, int cigar_cap,
                   int64_t* meta) {
    Graph& g = *(Graph*)h;
    const int align_mode = params[0], gap_mode = params[1], wb = params[2];
    const double wf = params[3] / 1e6;
    const int m = params[5];
    const int32_t o1 = params[6], e1 = params[7], o2 = params[8], e2 = params[9];
    const int32_t oe1 = o1 + e1, oe2 = o2 + e2, min_mis = params[10];
    const bool gap_on_right = params[11] != 0;
    const bool put_gap_at_end_flag = params[12] != 0;
    const bool ret_cigar = params[13] != 0;
    const bool inc_ps = params[14] != 0;  // -G path scores
    const bool local = align_mode == 1, extend = align_mode == 2;
    const bool banded = wb >= 0;
    const bool linear = gap_mode == 0, convex = gap_mode == 2;
    const int n_planes = linear ? 1 : (gap_mode == 1 ? 3 : 5);

    const int beg_index = g.node_id_to_index[beg_node_id];
    const int end_index = g.node_id_to_index[end_node_id];
    const int gn = end_index - beg_index + 1;
    const int w = banded ? wb + (int)(wf * qlen) : qlen;
    const int32_t TMIN = sizeof(T) == 2 ? KINT16_MIN : KINT32_MIN;
    const int32_t inf = std::max(std::max(TMIN + min_mis, TMIN + oe1),
                                 TMIN + oe2) + 512 * std::max(e1, e2);

    // subgraph reachability mask (abpoa_align_simd.c:1259-1269); persistent
    // workspace — per-alignment vector-of-vectors allocation dominated the
    // per-row overhead at 40k+ rows
    std::vector<uint8_t>& index_map = g.ws_index_map;
    index_map.assign(g.n(), 0);
    index_map[beg_index] = index_map[end_index] = 1;
    for (int i = beg_index; i < end_index - 1; ++i) {
        if (!index_map[i]) continue;
        for (int out_id : g.nodes[g.index_to_node_id[i]].out_ids)
            index_map[g.node_id_to_index[out_id]] = 1;
    }

    // filtered predecessor lists per dp row, flattened CSR (+ -G path score
    // per kept slot: ps keys by the ORIGINAL in-edge position, so it must be
    // computed here where that position is still known)
    std::vector<int32_t>& pre_flat = g.ws_pre;
    std::vector<int32_t>& pre_off = g.ws_pre_off;
    std::vector<int32_t>& pre_ps = g.ws_pre_ps;
    if ((int)pre_off.size() < gn + 1) pre_off.resize(gn + 1);
    pre_flat.clear();
    if (inc_ps) pre_ps.clear();
    pre_off[0] = pre_off[1] = 0;
    for (int i = 1; i < gn; ++i) {
        if (index_map[beg_index + i]) {
            int nid = g.index_to_node_id[beg_index + i];
            const auto& in_ids = g.nodes[nid].in_ids;
            for (size_t k = 0; k < in_ids.size(); ++k) {
                int p = g.node_id_to_index[in_ids[k]];
                if (index_map[p]) {
                    pre_flat.push_back(p - beg_index);
                    if (inc_ps)
                        pre_ps.push_back(incre_path_score(g, nid, (int)k));
                }
            }
        }
        pre_off[i + 1] = (int32_t)pre_flat.size();
    }
    struct PreView {
        const int32_t* flat; const int32_t* off;
        struct Range { const int32_t* b; const int32_t* e;
                       const int32_t* begin() const { return b; }
                       const int32_t* end() const { return e; } };
        Range operator[](int i) const {
            return {flat + off[i], flat + off[i + 1]};
        }
    };
    const PreView pre{pre_flat.data(), pre_off.data()};

    const int32_t remain_end = banded || params[4] > 0 ? g.max_remain[end_node_id] : 0;
    auto ad_beg = [&](int nid) {
        int r = qlen - (g.max_remain[nid] - remain_end - 1);
        return std::max(0, std::min(g.mpl[nid], r) - w);
    };
    auto ad_end = [&](int nid) {
        int r = qlen - (g.max_remain[nid] - remain_end - 1);
        return std::min(qlen, std::max(g.mpr[nid], r) + w);
    };

    DpPlanes<T> dp(g);
    dp.inf = inf;
    dp.start(gn, n_planes);

    // ---- first row --------------------------------------------------------
    if (banded) {
        g.mpl[beg_node_id] = g.mpr[beg_node_id] = 0;
        for (int out_id : g.nodes[beg_node_id].out_ids)
            if (index_map[g.node_id_to_index[out_id]])
                g.mpl[out_id] = g.mpr[out_id] = 1;
        dp.beg[0] = 0;
        dp.end[0] = ad_end(beg_node_id);
    } else {
        dp.beg[0] = 0;
        dp.end[0] = qlen;
    }

    auto append_row = [&](int i, int b, int e) { dp.append_row(i, b, e); };

    {
        int b0 = dp.beg[0], e0 = dp.end[0];
        append_row(0, b0, e0);
    }
    {
        int e0 = dp.end[0];
        int64_t p0 = dp.row_ptr[0];
        if (local) {
            for (int j = 0; j <= e0; ++j) {
                dp.H[p0 + j] = 0;
                if (n_planes >= 3) dp.E1[p0 + j] = dp.F1[p0 + j] = 0;
                if (n_planes >= 5) dp.E2[p0 + j] = dp.F2[p0 + j] = 0;
            }
        } else if (linear) {
            for (int j = 0; j <= e0; ++j)
                dp.H[p0 + j] = clamp_store<T>(-e1 * j);
        } else if (gap_mode == 1) {
            dp.H[p0] = 0; dp.E1[p0] = clamp_store<T>(-oe1);
            dp.F1[p0] = clamp_store<T>(inf);
            for (int j = 1; j <= e0; ++j) {
                dp.F1[p0 + j] = clamp_store<T>(-o1 - e1 * j);
                dp.H[p0 + j] = dp.F1[p0 + j];
                dp.E1[p0 + j] = clamp_store<T>(inf);
            }
        } else {
            dp.H[p0] = 0; dp.E1[p0] = clamp_store<T>(-oe1);
            dp.E2[p0] = clamp_store<T>(-oe2);
            dp.F1[p0] = dp.F2[p0] = clamp_store<T>(inf);
            for (int j = 1; j <= e0; ++j) {
                dp.F1[p0 + j] = clamp_store<T>(-o1 - e1 * j);
                dp.F2[p0 + j] = clamp_store<T>(-o2 - e2 * j);
                dp.H[p0 + j] = std::max(dp.F1[p0 + j], dp.F2[p0 + j]);
                dp.E1[p0 + j] = dp.E2[p0 + j] = clamp_store<T>(inf);
            }
        }
    }

    int32_t best_score = inf;
    int best_i = 0, best_j = 0, best_nid = beg_node_id;
    std::vector<int32_t> Mq, E1r, E2r, Hh;

    // query profile: qprof[k][j] = mat[k][query[j-1]], qprof[k][0] = 0 — one
    // gather pass per alignment so the per-row profile add is a contiguous
    // (vectorizable) load (the reference builds qp the same way,
    // abpoa_align_simd.c:463-580)
    std::vector<int32_t>& qprof = g.ws_qprof;
    if ((int64_t)qprof.size() < (int64_t)m * (qlen + 1))
        qprof.resize((int64_t)m * (qlen + 1));
    for (int k = 0; k < m; ++k) {
        int32_t* qp = qprof.data() + (int64_t)k * (qlen + 1);
        const int32_t* mk = mat + (int64_t)k * m;
        qp[0] = 0;
        for (int j = 1; j <= qlen; ++j) qp[j] = mk[query[j - 1]];
    }

    // ---- row loop ---------------------------------------------------------
    bool zdropped = false;
    for (int index_i = beg_index + 1; index_i < end_index && !zdropped; ++index_i) {
        if (!index_map[index_i]) continue;
        int i = index_i - beg_index;
        int nid = g.index_to_node_id[index_i];
        int b, e;
        if (banded) {
            b = ad_beg(nid);
            e = ad_end(nid);
            int mpb = INT32_MAX;
            for (int p : pre[i]) mpb = std::min(mpb, dp.beg[p]);
            if (b < mpb) b = mpb;
        } else { b = 0; e = qlen; }
        append_row(i, b, e);
        int width = e - b + 1;
        Mq.assign(width, inf);
        // linear-gap E candidates are (pred H - e1); uncovered cells carry
        // inf-e1 in the oracle's full-width arithmetic — replicate exactly
        E1r.assign(width, linear ? inf - e1 : inf);
        if (convex) E2r.assign(width, inf);
        const uint8_t base = g.nodes[nid].base;
        const int32_t* qrow = qprof.data() + (int64_t)base * (qlen + 1);

        for (int32_t t = pre_off[i]; t < pre_off[i + 1]; ++t) {
            const int p = pre_flat[t];
            // -G adds the pred's path score to every contribution
            // (oracle.py:232-245; reference abpoa_graph.c:429-437); the
            // ps==0 bodies keep the non-G inner loops byte-for-byte intact
            const int32_t ps = inc_ps ? pre_ps[t] : 0;
            const int pb = dp.beg[p], pe = dp.end[p];
            const int64_t pp = dp.row_ptr[p];
            // M from pred H at j-1: overlap of [b,e] with [pb+1, pe+1]
            {
                const int lo = std::max(b, pb + 1), hi = std::min(e, pe + 1);
                const T* Hp = dp.H.data() + pp - pb;  // Hp[j-1] valid
                int32_t* Mqp = Mq.data() - b;
                if (ps == 0) {
                    for (int j = lo; j <= hi; ++j)
                        Mqp[j] = std::max(Mqp[j], (int32_t)Hp[j - 1]);
                } else {
                    for (int j = lo; j <= hi; ++j)
                        Mqp[j] = std::max(Mqp[j], (int32_t)Hp[j - 1] + ps);
                }
            }
            // E from pred at j: overlap of [b,e] with [pb, pe]
            {
                const int lo = std::max(b, pb), hi = std::min(e, pe);
                if (linear) {
                    const T* Hp = dp.H.data() + pp - pb;
                    int32_t* Ep = E1r.data() - b;
                    const int32_t d = e1 - ps;
                    for (int j = lo; j <= hi; ++j)
                        Ep[j] = std::max(Ep[j], (int32_t)Hp[j] - d);
                } else {
                    const T* E1p = dp.E1.data() + pp - pb;
                    int32_t* Ep = E1r.data() - b;
                    if (ps == 0) {
                        for (int j = lo; j <= hi; ++j)
                            Ep[j] = std::max(Ep[j], (int32_t)E1p[j]);
                    } else {
                        for (int j = lo; j <= hi; ++j)
                            Ep[j] = std::max(Ep[j], (int32_t)E1p[j] + ps);
                    }
                    if (convex) {
                        const T* E2p = dp.E2.data() + pp - pb;
                        int32_t* E2o = E2r.data() - b;
                        if (ps == 0) {
                            for (int j = lo; j <= hi; ++j)
                                E2o[j] = std::max(E2o[j], (int32_t)E2p[j]);
                        } else {
                            for (int j = lo; j <= hi; ++j)
                                E2o[j] = std::max(E2o[j], (int32_t)E2p[j] + ps);
                        }
                    }
                }
            }
        }
        if (local && b == 0) {
            // H[-1] treated as 0; under -G the lead carries the path score,
            // so the seed is max over preds of (0 + ps) (oracle.py:237-241)
            int32_t lead = 0;
            if (inc_ps && pre_off[i] < pre_off[i + 1]) {
                lead = pre_ps[pre_off[i]];
                for (int32_t t = pre_off[i] + 1; t < pre_off[i + 1]; ++t)
                    lead = std::max(lead, pre_ps[t]);
            }
            if (Mq[0] < lead) Mq[0] = lead;
        }
        // add query profile; Hhat = max(M+q, E) — contiguous, vectorizable
        Hh.resize(width);  // fully overwritten below; no fill needed
        {
            const int32_t* qj = qrow + b;
            if (convex) {
                for (int j = 0; j < width; ++j) {
                    Mq[j] += qj[j];
                    Hh[j] = std::max(std::max(Mq[j], E1r[j]), E2r[j]);
                }
            } else {
                for (int j = 0; j < width; ++j) {
                    Mq[j] += qj[j];
                    Hh[j] = std::max(Mq[j], E1r[j]);
                }
            }
        }
        int64_t pi = dp.row_ptr[i];
        if (linear) {
            // in-row chain on H plane: H[j] = max(H[j], H[j-1]-e1)
            int32_t prev = Hh[0];
            dp.H[pi] = clamp_store<T>(local ? std::max(prev, 0) : prev);
            for (int j = 1; j < width; ++j) {
                int32_t v = std::max(Hh[j], prev - e1);
                prev = v;
                dp.H[pi + j] = clamp_store<T>(local ? std::max(v, 0) : v);
            }
        } else {
            // F chains: F[b]=Mq[b]-oe; F[j]=max(Hh[j-1]-oe, F[j-1]-e).
            // The carry is latency-bound and unavoidable (a log-doubling
            // vectorized form was measured SLOWER at typical ~220-cell
            // bands), so keep ONLY the carry sequential and finalize
            // H/E elementwise in a separate autovectorized pass.
            T* F1row = dp.F1.data() + pi;
            T* E1row = dp.E1.data() + pi;
            T* Hrow = dp.H.data() + pi;
            if (convex) {
                T* F2row = dp.F2.data() + pi;
                T* E2row = dp.E2.data() + pi;
                int32_t f1 = Mq[0] - oe1, f2 = Mq[0] - oe2;
                F1row[0] = clamp_store<T>(f1);
                F2row[0] = clamp_store<T>(f2);
                for (int j = 1; j < width; ++j) {
                    f1 = std::max(Hh[j - 1] - oe1, f1 - e1);
                    f2 = std::max(Hh[j - 1] - oe2, f2 - e2);
                    F1row[j] = clamp_store<T>(f1);
                    F2row[j] = clamp_store<T>(f2);
                }
                for (int j = 0; j < width; ++j) {
                    int32_t hrow = std::max(std::max(Hh[j], (int32_t)F1row[j]),
                                            (int32_t)F2row[j]);
                    if (local) hrow = std::max(hrow, 0);
                    int32_t e1n = std::max((int32_t)(E1r[j] - e1), hrow - oe1);
                    int32_t e2n = std::max((int32_t)(E2r[j] - e2), hrow - oe2);
                    if (local) {
                        e1n = std::max(e1n, 0);
                        e2n = std::max(e2n, 0);
                    }
                    Hrow[j] = clamp_store<T>(hrow);
                    E1row[j] = clamp_store<T>(e1n);
                    E2row[j] = clamp_store<T>(e2n);
                }
            } else {
                int32_t f1 = Mq[0] - oe1;
                F1row[0] = clamp_store<T>(f1);
                for (int j = 1; j < width; ++j) {
                    f1 = std::max(Hh[j - 1] - oe1, f1 - e1);
                    F1row[j] = clamp_store<T>(f1);
                }
                const int32_t dead = local ? 0 : inf;
                for (int j = 0; j < width; ++j) {
                    int32_t hrow = std::max(Hh[j], (int32_t)F1row[j]);
                    if (local) hrow = std::max(hrow, 0);
                    // affine E kill when F strictly dominates H
                    // (abpoa_align_simd.c:926-930)
                    int32_t e1n = (hrow == Hh[j])
                        ? std::max((int32_t)(E1r[j] - e1), hrow - oe1) : dead;
                    Hrow[j] = clamp_store<T>(hrow);
                    E1row[j] = clamp_store<T>(e1n);
                }
            }
        }

        // ---- row max: local/extend scoring + adaptive band ----------------
        if (local || extend || banded) {
            // vectorizable max reduction, then first/last-equal scans
            const T* Hp = dp.H.data() + pi;
            int32_t mx = inf;
            for (int j = 0; j < width; ++j) mx = std::max(mx, (int32_t)Hp[j]);
            int left = -1, right = -1;
            if (mx > inf) {
                int j = 0;
                while ((int32_t)Hp[j] != mx) ++j;
                left = b + j;
                j = width - 1;
                while ((int32_t)Hp[j] != mx) --j;
                right = b + j;
            }
            if (local) {
                if (mx > best_score) { best_score = mx; best_i = i; best_j = left; }
            } else if (extend) {
                if (mx > best_score) {
                    best_score = mx; best_i = i; best_j = right; best_nid = nid;
                } else if (params[4] > 0) {
                    int delta = g.max_remain[best_nid] - g.max_remain[nid];
                    if (best_score - mx > params[4] + e1 * std::abs(delta - (right - best_j))) {
                        zdropped = true;
                        break;
                    }
                }
            }
            if (banded) {
                for (int out_id : g.nodes[nid].out_ids) {
                    if (right + 1 > g.mpr[out_id]) g.mpr[out_id] = right + 1;
                    if (left + 1 < g.mpl[out_id]) g.mpl[out_id] = left + 1;
                }
            }
        }
    }

    // ---- global best over the end node's in-rows --------------------------
    if (align_mode == 0) {
        for (int in_id : g.nodes[end_node_id].in_ids) {
            int idx = g.node_id_to_index[in_id];
            if (!index_map[idx]) continue;
            int i = idx - beg_index;
            int e = std::min(qlen, (int)dp.end[i]);
            int32_t v = dp.h(i, e);
            if (v > best_score) { best_score = v; best_i = i; best_j = e; }
        }
    }
    meta[0] = best_score;
    if (!ret_cigar) { meta[7] = 0; return 0; }

    // ---- backtrack (reference op priority, abpoa_align_simd.c:116-458) ----
    CigBuf cig{cigar_out, cigar_cap};
    int i = best_i, j = best_j;
    int start_i = best_i, start_j = best_j;
    int nid = g.index_to_node_id[i + beg_index];
    if (best_j < qlen) cig.push(1, qlen - best_j, -1, qlen - 1);
    int look_gap = put_gap_at_end_flag ? 1 : 0;
    int cur_op = 0x1F;  // ALL
    const int M_OP = 1, E1_OP = 2, E2_OP = 4, F1_OP = 8, F2_OP = 16;
    while (i > 0 && j > 0) {
        if (local && dp.h(i, j) == 0) break;
        start_i = i; start_j = j;
        int32_t s = mat[(int64_t)g.nodes[nid].base * m + query[j - 1]];
        bool is_match = g.nodes[nid].base == query[j - 1];
        bool hit = false;
        int32_t Hij = dp.h(i, j);

        auto try_match = [&]() -> bool {
            for (int32_t t = pre_off[i]; t < pre_off[i + 1]; ++t) {
                const int p = pre_flat[t];
                const int32_t ps = inc_ps ? pre_ps[t] : 0;
                if (j - 1 < dp.beg[p] || j - 1 > dp.end[p]) continue;
                if (dp.h(p, j - 1) + s + ps == Hij) {
                    cig.push(0, 1, nid, j - 1);
                    i = p; --j; nid = g.index_to_node_id[i + beg_index];
                    cur_op = 0x1F;
                    meta[5]++; if (is_match) meta[6]++;
                    return true;
                }
            }
            return false;
        };

        if (!gap_on_right && look_gap == 0 && (linear || (cur_op & M_OP)))
            hit = try_match();

        if (!hit) {  // deletion
            if (linear) {
                for (int32_t t = pre_off[i]; t < pre_off[i + 1]; ++t) {
                    const int p = pre_flat[t];
                    const int32_t ps = inc_ps ? pre_ps[t] : 0;
                    if (j < dp.beg[p] || j > dp.end[p]) continue;
                    if (dp.h(p, j) - e1 + ps == Hij) {
                        cig.push(2, 1, nid, j - 1);
                        i = p; nid = g.index_to_node_id[i + beg_index];
                        hit = true; look_gap = 0;
                        break;
                    }
                }
            } else if (cur_op & (E1_OP | E2_OP)) {
                for (int32_t t = pre_off[i]; t < pre_off[i + 1]; ++t) {
                    const int p = pre_flat[t];
                    const int32_t ps = inc_ps ? pre_ps[t] : 0;
                    if (j < dp.beg[p] || j > dp.end[p]) continue;
                    bool done = false;
                    if (cur_op & E1_OP) {
                        bool cond = (cur_op & M_OP)
                            ? (Hij == dp.e1(p, j) + ps)
                            : (dp.e1(i, j) == dp.e1(p, j) - e1 + ps);
                        if (cond) {
                            cur_op = (dp.h(p, j) - oe1 == dp.e1(p, j))
                                ? (M_OP | F1_OP | F2_OP) : E1_OP;
                            cig.push(2, 1, nid, j - 1);
                            i = p; nid = g.index_to_node_id[i + beg_index];
                            hit = done = true; look_gap = 0;
                        }
                    }
                    if (!done && convex && (cur_op & E2_OP)) {
                        bool cond = (cur_op & M_OP)
                            ? (Hij == dp.e2(p, j) + ps)
                            : (dp.e2(i, j) == dp.e2(p, j) - e2 + ps);
                        if (cond) {
                            cur_op = (dp.h(p, j) - oe2 == dp.e2(p, j))
                                ? (M_OP | F1_OP | F2_OP) : E2_OP;
                            cig.push(2, 1, nid, j - 1);
                            i = p; nid = g.index_to_node_id[i + beg_index];
                            hit = done = true; look_gap = 0;
                        }
                    }
                    if (done) break;
                }
            }
        }

        if (!hit) {  // insertion
            if (linear) {
                if (dp.h(i, j - 1) - e1 == Hij) {
                    cig.push(1, 1, nid, j - 1);
                    --j; look_gap = 0; hit = true; meta[5]++;
                }
            } else if (cur_op & (F1_OP | F2_OP)) {
                bool got = false;
                if (cur_op & F1_OP) {
                    bool gate = (cur_op & M_OP) ? (Hij == dp.f1(i, j)) : true;
                    if (gate) {
                        if (dp.h(i, j - 1) - oe1 == dp.f1(i, j)) {
                            cur_op = M_OP | E1_OP | E2_OP; got = true;
                        } else if (dp.f1(i, j - 1) - e1 == dp.f1(i, j)) {
                            cur_op = F1_OP; got = true;
                        }
                    }
                }
                if (!got && convex && (cur_op & F2_OP)) {
                    bool gate = (cur_op & M_OP) ? (Hij == dp.f2(i, j)) : true;
                    if (gate) {
                        if (dp.h(i, j - 1) - oe2 == dp.f2(i, j)) {
                            cur_op = M_OP | E1_OP | E2_OP; got = true;
                        } else if (dp.f2(i, j - 1) - e2 == dp.f2(i, j)) {
                            cur_op = F2_OP; got = true;
                        }
                    }
                }
                if (got) {
                    cig.push(1, 1, nid, j - 1);
                    --j; look_gap = 0; hit = true; meta[5]++;
                }
            }
        }

        if (!hit && (linear || (cur_op & M_OP))) {
            hit = try_match();
            if (hit) look_gap = 0;
        }
        if (!hit) return -1;  // backtrack failure -> caller falls back
    }
    if (j > 0) cig.push(1, j, -1, j - 1);
    if (cig.overflow) return -2;
    // reverse (reference emits back-to-front then reverses)
    for (int a = 0, bb = cig.n - 1; a < bb; ++a, --bb)
        std::swap(cigar_out[a], cigar_out[bb]);
    meta[1] = g.index_to_node_id[start_i + beg_index];
    meta[2] = g.index_to_node_id[best_i + beg_index];
    meta[3] = start_j - 1;
    meta[4] = best_j - 1;
    meta[7] = cig.n;
    return 0;
}
