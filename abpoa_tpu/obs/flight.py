"""Worker flight recorder: the black box a SIGKILL cannot erase.

The pool supervisor (parallel/pool.py) can kill a worker at any instant —
deadline expiry, RSS breach, an injected SIGSEGV, or the kernel's OOM
killer beating it to the punch. Everything the worker knew at that moment
(which span was open, the last dispatch signature and rung, the RSS
trend, the faults it had absorbed) dies with the process — unless it was
already on disk. This module keeps an always-on, bounded in-memory record
and persists it via atomic rename on every heartbeat (~1 s), so the
freshest dump a dead worker leaves behind is at most one heartbeat stale.

Layout on disk (``ABPOA_TPU_FLIGHT_DIR``, default
``~/.cache/abpoa_tpu/flight``):

- ``worker-<pid>.json``   the live dump, rewritten atomically each beat
- ``dump-<rid>-a<N>-p<pid>.json``  a harvested dump: when the supervisor
  kills (or observes the death of) a worker, it renames the live dump,
  enriching it with the parent-observed cause (`harvest` block) — the
  artifact `abpoa-tpu why` renders and the archive record points at.

Overhead contract: per span it is two list operations on a bounded
stack; the JSON persist happens on the heartbeat thread (already awake
to read RSS), never on the job's execution path. Recording requires the
span tracer armed (pool workers arm it in worker_init); outside a pool
worker nothing here is installed and `trace.span` pays one extra `is
None` check.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

SCHEMA = "abpoa-tpu-flight"
SCHEMA_VERSION = 1

# bounded tails: recent closed spans / faults / RSS samples kept in a dump
SPAN_KEEP = 48
RSS_KEEP = 64

# span categories that count as "a dispatch" for last_dispatch attribution
_DISPATCH_CATS = ("dp", "fused", "compile")


def flight_dir() -> str:
    d = os.environ.get("ABPOA_TPU_FLIGHT_DIR")
    if d:
        return d
    base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    return os.path.join(base, "abpoa_tpu", "flight")


def worker_dump_path(pid: int, dirpath: Optional[str] = None) -> str:
    return os.path.join(dirpath or flight_dir(), f"worker-{pid}.json")


class FlightRecorder:
    """One worker process's always-on bounded record + atomic persister."""

    def __init__(self, path: str, label: str = "") -> None:
        self.path = path
        self.label = label
        self.pid = os.getpid()
        self.t0 = time.perf_counter()
        self.beats = 0
        self.job: Optional[dict] = None     # current job context
        self.open_spans: list = []          # stack of (name, cat, t0, args)
        self.last_dispatch: Optional[dict] = None
        self.rss: list = []                 # [(t_s, bytes)] bounded tail
        self._lock = threading.Lock()       # job thread vs heartbeat thread

    # ----------------------------------------------------------- recording
    def push_open(self, name: str, cat: str, t0: float,
                  args: Optional[dict]) -> None:
        self.open_spans.append((name, cat, t0, args))

    def pop_open(self, name: str, cat: str, t0: float, dur: float,
                 args: Optional[dict]) -> None:
        if self.open_spans and self.open_spans[-1][0] == name:
            self.open_spans.pop()
        if cat in _DISPATCH_CATS:
            self.last_dispatch = {"name": name, "cat": cat,
                                  "t_s": round(t0 - self.t0, 4),
                                  "dur_s": round(dur, 6),
                                  "args": dict(args) if args else None}

    def begin_job(self, rid: str, attempt: int, kind: str,
                  label: str = "") -> None:
        """New job context; persisted IMMEDIATELY so even a kill that
        lands before the first heartbeat leaves a dump naming the job."""
        with self._lock:
            self.job = {"rid": rid or None, "attempt": int(attempt),
                        "kind": kind, "label": label,
                        "t_start_s": round(time.perf_counter() - self.t0, 4),
                        "status": "running"}
        self.persist()

    def end_job(self, status: str = "done") -> None:
        with self._lock:
            if self.job is not None:
                self.job["status"] = status

    def beat(self, rss_bytes: int) -> None:
        """One heartbeat: append the RSS sample, persist the dump."""
        self.beats += 1
        self.rss.append((round(time.perf_counter() - self.t0, 3),
                         int(rss_bytes)))
        if len(self.rss) > RSS_KEEP:
            del self.rss[:len(self.rss) - RSS_KEEP]
        self.persist()

    # ----------------------------------------------------------- rendering
    def snapshot(self) -> dict:
        # note: the package attribute `report` is the accessor FUNCTION
        # (obs/__init__ re-exports it), so import from the module itself
        from .report import report as _get_report
        from . import trace as _trace
        t_now = time.perf_counter()
        spans = []
        for ev in _trace.tracer().tail(SPAN_KEEP):
            kind, name, cat, ts, dur, _tid, args, req = ev
            if kind != "X":
                continue
            rec = {"name": name, "cat": cat,
                   "t_s": round(ts - self.t0, 4), "dur_s": round(dur, 6)}
            if args:
                rec["args"] = args
            if req:
                rec["rid"], rec["attempt"] = req[0], req[1]
            spans.append(rec)
        with self._lock:
            job = dict(self.job) if self.job else None
            open_spans = [{"name": n, "cat": c,
                           "t_s": round(t0 - self.t0, 4),
                           "elapsed_s": round(t_now - t0, 4),
                           "args": dict(a) if a else None}
                          for n, c, t0, a in self.open_spans]
        if job is not None and job.get("status") == "running":
            job["elapsed_s"] = round(
                t_now - self.t0 - job.get("t_start_s", 0.0), 4)
        rep = _get_report()
        return {
            "schema": SCHEMA,
            "schema_version": SCHEMA_VERSION,
            "pid": self.pid,
            "label": self.label,
            "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "uptime_s": round(t_now - self.t0, 3),
            "beats": self.beats,
            "job": job,
            "open_spans": open_spans,
            "last_dispatch": self.last_dispatch,
            "recent_spans": spans,
            "faults": list(rep.faults[-16:]),
            "rss": list(self.rss),
        }

    def persist(self) -> None:
        """Atomic-rename write; failure is swallowed — the recorder must
        never fail the work it records."""
        try:
            tmp = f"{self.path}.tmp.{self.pid}"
            with open(tmp, "w") as fp:
                json.dump(self.snapshot(), fp)
            os.replace(tmp, self.path)
        except (OSError, ValueError, TypeError):
            pass


# --------------------------------------------------------------------------- #
# module registry (worker side)                                               #
# --------------------------------------------------------------------------- #

_REC: Optional[FlightRecorder] = None


def install(label: str = "", path: Optional[str] = None) -> FlightRecorder:
    """Arm the flight recorder for THIS process (pool worker_init). The
    span tracer must already be enabled — the recorder's recent-span tail
    reads the tracer ring."""
    global _REC
    from . import trace as _trace
    if path is None:
        path = worker_dump_path(os.getpid())
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    except OSError:
        pass
    _REC = FlightRecorder(path, label=label)
    _trace.set_flight(_REC)
    return _REC


def uninstall() -> None:
    global _REC
    from . import trace as _trace
    _trace.set_flight(None)
    _REC = None


def shutdown() -> None:
    """Clean worker exit: remove the live dump (nothing died — a stale
    `worker-<pid>.json` would otherwise accumulate per pid and could be
    mis-harvested by a future worker reusing the pid)."""
    global _REC
    rec = _REC
    uninstall()
    if rec is not None:
        try:
            os.unlink(rec.path)
        except OSError:
            pass


def recorder() -> Optional[FlightRecorder]:
    return _REC


def begin_job(rid: str, attempt: int, kind: str, label: str = "") -> None:
    if _REC is not None:
        _REC.begin_job(rid, attempt, kind, label)


def end_job(status: str = "done") -> None:
    if _REC is not None:
        _REC.end_job(status)


def beat(rss_bytes: int) -> None:
    if _REC is not None:
        _REC.beat(rss_bytes)


# --------------------------------------------------------------------------- #
# harvest (supervisor side)                                                   #
# --------------------------------------------------------------------------- #

def harvest(pid: int, reason: str, rid: str = "", attempt: int = 0,
            detail: str = "", dirpath: Optional[str] = None) -> Optional[str]:
    """Collect a dead worker's live dump: read ``worker-<pid>.json``,
    enrich it with the parent-observed cause of death (`harvest` block —
    the worker cannot record its own SIGKILL), and move it to a stable
    ``dump-…`` name the archive record can reference. Returns the dump
    path, or None when the worker never persisted (died before its first
    beat with no job begun, or the dir is unwritable)."""
    dirpath = dirpath or flight_dir()
    src = worker_dump_path(pid, dirpath)
    try:
        with open(src) as fp:
            dump = json.load(fp)
    except (OSError, ValueError):
        return None
    dump["harvest"] = {
        "reason": reason,
        "detail": detail[:300],
        "request_id": rid or (dump.get("job") or {}).get("rid"),
        "attempt": attempt or (dump.get("job") or {}).get("attempt"),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if dump.get("job") and dump["job"].get("status") == "running":
        dump["job"]["status"] = f"died:{reason}"
    tag = dump["harvest"]["request_id"] or "nojob"
    dest = os.path.join(
        dirpath, f"dump-{tag}-a{dump['harvest']['attempt'] or 0}-p{pid}.json")
    try:
        with open(dest, "w") as fp:
            json.dump(dump, fp)
        os.unlink(src)
    except OSError:
        return None
    # bounded like --trace-dir: deaths are rare enough that the listdir
    # can run on every harvest (no amortization needed)
    _prune_dumps(dirpath)
    return dest


def max_dumps() -> int:
    return int(os.environ.get("ABPOA_TPU_FLIGHT_DIR_MAX", "256"))


def _prune_dumps(dirpath: str) -> None:
    """Keep only the newest `max_dumps()` harvested dumps — a multi-day
    soak under recurring kill conditions must not fill the disk with one
    permanent file per death."""
    try:
        names = [n for n in os.listdir(dirpath)
                 if n.startswith("dump-") and n.endswith(".json")]
        keep = max_dumps()
        if len(names) <= keep:
            return
        full = sorted((os.path.getmtime(os.path.join(dirpath, n)), n)
                      for n in names)
        for _mt, n in full[:len(names) - keep]:
            os.unlink(os.path.join(dirpath, n))
    except OSError:
        pass
