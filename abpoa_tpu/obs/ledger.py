"""Performance-trajectory ledger: the cross-run perf memory behind
`abpoa-tpu perf`.

Every perf-bearing entrypoint — bench.py, the five `tools/*_gate.py`
gates, the serve/map/shard/fleet smoke soaks, and `abpoa-tpu warm` —
appends ONE schema-versioned JSONL record to ``PERF_LEDGER.jsonl``:
git sha, host fingerprint, device kind, route, K/mesh/Qp rung, reads/s,
CUPS, MFU, occupancy, p50/p95/p99, compile misses, gate verdict. The
ledger is what turns 19 loose BENCH_*/MULTICHIP_* files and five
hand-re-anchored baselines into a *trajectory*: "has reads/s drifted
over the last N runs" becomes a query, and the drift gate
(`abpoa-tpu perf --gate`) compares each run against the trailing-window
MEDIAN of its own (source, workload) group instead of a single staleable
baseline number.

Write discipline is `obs/archive.py`'s, verbatim: one ``os.write`` on an
``O_APPEND`` descriptor (same-host appends can never interleave bytes),
rotation past ``ABPOA_TPU_LEDGER_MAX_MB`` (default 8 MB) to
``PERF_LEDGER.jsonl.1`` under a process lock with a re-stat, one rotated
generation kept. ``ABPOA_TPU_LEDGER=0`` disables; ``ABPOA_TPU_LEDGER_DIR``
redirects (CI keeps the ledger in the workspace so the artifact/cache
steps can round-trip it across runs). Append failure never fails the
work that produced the record.

Records carry an idempotency ``key`` so the backfill importer
(`tools/ledger_backfill.py`) can re-run without duplicating history:
`append_unique` skips a record whose key is already in the window.
"""
from __future__ import annotations

import hashlib
import json
import os
import platform
import subprocess
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

LEDGER_FILE = "PERF_LEDGER.jsonl"
LEDGER_SCHEMA_VERSION = 1

# the drift gate's defaults: a run regresses when a metric falls below
# RATIO x the trailing-window median of its own (source, workload) group;
# groups with fewer than MIN_HISTORY prior records pass vacuously (a new
# workload must not fail its own first run)
DRIFT_RATIO = 0.6
DRIFT_MIN_HISTORY = 3
DRIFT_SPAN = 12
DRIFT_METRICS = ("reads_per_sec", "cell_updates_per_sec")

_ROTATE_LOCK = threading.Lock()
_GIT_SHA_CACHE: Optional[str] = None


def ledger_enabled() -> bool:
    return os.environ.get("ABPOA_TPU_LEDGER", "1") not in ("0", "off")


def ledger_dir() -> str:
    d = os.environ.get("ABPOA_TPU_LEDGER_DIR")
    if d:
        return d
    base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    return os.path.join(base, "abpoa_tpu", "ledger")


def ledger_path() -> str:
    return os.path.join(ledger_dir(), LEDGER_FILE)


def max_bytes() -> int:
    return int(float(os.environ.get("ABPOA_TPU_LEDGER_MAX_MB", "8")) * 1e6)


def git_sha() -> str:
    """Short sha of the working tree, "" outside a repo / without git.
    Cached per process: the ledger appends from tight gate loops."""
    global _GIT_SHA_CACHE
    if _GIT_SHA_CACHE is None:
        try:
            _GIT_SHA_CACHE = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip()
        except (OSError, subprocess.SubprocessError):
            _GIT_SHA_CACHE = ""
    return _GIT_SHA_CACHE


def host_fingerprint() -> Dict[str, object]:
    return {
        "node": platform.node(),
        "machine": platform.machine(),
        "python": platform.python_version(),
        "cpus": os.cpu_count() or 1,
    }


def make_record(source: str, *, workload: str = "", device: str = "",
                route: str = "", rung: Optional[dict] = None,
                reads_per_sec: Optional[float] = None,
                cell_updates_per_sec: Optional[float] = None,
                mfu: Optional[float] = None,
                occupancy: Optional[float] = None,
                read_wall_ms: Optional[dict] = None,
                compile_misses: Optional[int] = None,
                verdict: Optional[str] = None,
                ts: Optional[str] = None,
                key: Optional[str] = None,
                extra: Optional[dict] = None) -> dict:
    """One canonical ledger record. Every appender goes through here so
    the schema-golden test pins ONE shape; `rung` is the compile-rung
    coordinate ({"K":..,"mesh":..,"Qp":..} — absent axes omitted), and
    `key` is the idempotency handle (derived from source+ts when the
    caller has no natural one)."""
    ts = ts or time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    rec = {
        "ts": ts,
        "schema_version": LEDGER_SCHEMA_VERSION,
        "source": source,
        "workload": workload,
        "git_sha": git_sha(),
        "host": host_fingerprint(),
        "device": device,
        "route": route,
        "rung": dict(rung or {}),
        "reads_per_sec": _num(reads_per_sec),
        "cell_updates_per_sec": _num(cell_updates_per_sec),
        "mfu": _num(mfu),
        "occupancy": _num(occupancy),
        "read_wall_ms": dict(read_wall_ms) if read_wall_ms else None,
        "compile_misses": compile_misses,
        "verdict": verdict,
    }
    if key is None:
        key = hashlib.sha1(
            f"{source}|{workload}|{ts}|{reads_per_sec}".encode()
        ).hexdigest()[:16]
    rec["key"] = key
    if extra:
        rec["extra"] = extra
    return rec


def _num(v) -> Optional[float]:
    if v is None:
        return None
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return round(f, 6)


def append_record(rec: dict) -> Optional[str]:
    """Append one ledger record. Same contract as archive.append_record:
    single O_APPEND write, rotate past the cap, failure returns None and
    never raises into the caller's perf run."""
    if not ledger_enabled():
        return None
    path = ledger_path()
    data = (json.dumps(rec) + "\n").encode()
    try:
        os.makedirs(ledger_dir(), exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
        _rotate_if_needed(path)
    except OSError:
        return None
    return path


def append_unique(rec: dict, path: Optional[str] = None) -> Optional[str]:
    """Append unless a record with the same idempotency key already
    exists — the backfill importer's re-run-safe entrypoint."""
    key = rec.get("key")
    if key and any(r.get("key") == key for r in read_window(0, path=path)):
        return None
    return append_record(rec)


def _rotate_if_needed(path: str) -> None:
    with _ROTATE_LOCK:
        try:
            if os.path.getsize(path) <= max_bytes():
                return
            os.replace(path, path + ".1")  # drops any previous .1
        except OSError:
            pass


def read_window(n: int, path: Optional[str] = None) -> List[dict]:
    """The newest `n` ledger records, oldest-first, rotated generation
    included; unparseable lines skipped, never fatal."""
    path = path or ledger_path()
    lines: List[str] = []
    for p in (path + ".1", path):
        try:
            with open(p) as fp:
                lines.extend(fp.read().splitlines())
        except OSError:
            continue
    out: List[dict] = []
    for line in lines[-n:] if n else lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def append_and_verify(rec: dict) -> List[str]:
    """Append one record and read it straight back: the smoke soaks'
    self-check that their run actually landed in the trajectory. Returns
    failure strings (empty when clean, or when the ledger is disabled —
    an operator opt-out must not fail a smoke)."""
    if not ledger_enabled():
        return []
    if append_record(rec) is None:
        return [f"ledger append failed for source={rec.get('source')!r}"]
    match = [r for r in read_window(0) if r.get("key") == rec.get("key")]
    if not match:
        return [f"ledger record key={rec.get('key')!r} missing after append"]
    return [f"ledger record lint: {p}" for p in lint_record(match[-1])]


REQUIRED_KEYS = ("ts", "schema_version", "source", "workload", "git_sha",
                 "host", "device", "route", "rung", "reads_per_sec",
                 "cell_updates_per_sec", "mfu", "occupancy", "read_wall_ms",
                 "compile_misses", "verdict", "key")


def lint_record(rec: dict) -> List[str]:
    """Schema complaints for one record (empty = clean). The smokes
    assert their appended record lints; the schema-golden test pins the
    same contract."""
    problems: List[str] = []
    for k in REQUIRED_KEYS:
        if k not in rec:
            problems.append(f"missing key {k!r}")
    if rec.get("schema_version") != LEDGER_SCHEMA_VERSION:
        problems.append(f"schema_version {rec.get('schema_version')!r} != "
                        f"{LEDGER_SCHEMA_VERSION}")
    if not rec.get("source"):
        problems.append("empty source")
    if not rec.get("key"):
        problems.append("empty idempotency key")
    for k in ("rung", "host"):
        if k in rec and not isinstance(rec[k], dict):
            problems.append(f"{k} is not a dict")
    if rec.get("read_wall_ms") is not None \
            and not isinstance(rec["read_wall_ms"], dict):
        problems.append("read_wall_ms is not a p50/p95/p99 dict")
    for m in ("reads_per_sec", "cell_updates_per_sec", "mfu", "occupancy"):
        v = rec.get(m)
        if v is not None and not isinstance(v, (int, float)):
            problems.append(f"{m} is not numeric")
    if rec.get("verdict") not in (None, "pass", "fail"):
        problems.append(f"verdict {rec.get('verdict')!r} not in "
                        "(None, 'pass', 'fail')")
    return problems


# ---------------------------------------------------------------- drift

def group_key(rec: dict) -> Tuple[str, str]:
    return (str(rec.get("source") or ""), str(rec.get("workload") or ""))


def group_records(window: Sequence[dict]) -> Dict[Tuple[str, str],
                                                  List[dict]]:
    """Records bucketed by (source, workload), ledger order preserved.
    Drift is only meaningful within a group: bench sim10k reads/s and a
    smoke soak's reads/s are different workloads on different payloads
    and must never median together."""
    groups: Dict[Tuple[str, str], List[dict]] = {}
    for rec in window:
        groups.setdefault(group_key(rec), []).append(rec)
    return groups


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def drift_check(window: Sequence[dict],
                metrics: Sequence[str] = DRIFT_METRICS,
                ratio: float = DRIFT_RATIO,
                min_history: int = DRIFT_MIN_HISTORY,
                span: int = DRIFT_SPAN,
                slowdown: float = 1.0) -> List[dict]:
    """Drift verdicts: for every (source, workload) group, compare the
    NEWEST record's metrics against the median of up to `span` trailing
    records. A metric regresses when current < ratio x median. Groups
    with < min_history prior records are reported `ok` with
    history=short (a fresh workload's first runs never self-fail).
    `slowdown` divides the current values first — the gate's
    --inject-slowdown self-test."""
    verdicts: List[dict] = []
    for (source, workload), recs in sorted(group_records(window).items()):
        cur, hist = recs[-1], recs[:-1][-span:]
        for m in metrics:
            cv = cur.get(m)
            if cv is None:
                continue
            cv = float(cv) / max(slowdown, 1e-9)
            hvals = [float(r[m]) for r in hist
                     if isinstance(r.get(m), (int, float))]
            v = {"source": source, "workload": workload, "metric": m,
                 "current": round(cv, 3), "n_history": len(hvals)}
            if len(hvals) < min_history:
                v.update(ok=True, median=None, note="history<min")
            else:
                med = _median(hvals)
                v.update(median=round(med, 3),
                         floor=round(ratio * med, 3),
                         ok=(med <= 0) or (cv >= ratio * med))
            verdicts.append(v)
    return verdicts


# ------------------------------------------------------------ rendering

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(vals: Sequence[float], width: int = 24) -> str:
    vals = [float(v) for v in vals if isinstance(v, (int, float))]
    if not vals:
        return ""
    vals = vals[-width:]
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[3] * len(vals)
    return "".join(_SPARK[min(len(_SPARK) - 1,
                              int((v - lo) / (hi - lo) * (len(_SPARK) - 1)))]
                   for v in vals)


def render_trajectory(window: Sequence[dict],
                      metrics: Sequence[str] = DRIFT_METRICS) -> str:
    """The `abpoa-tpu perf` table: one row per (source, workload) x
    metric with count, median, latest, and a sparkline of the series."""
    if not window:
        return "perf ledger: no records (run a gate, bench, or " \
               "tools/ledger_backfill.py)"
    lines = [f"perf ledger: {len(window)} records @ {ledger_path()}",
             f"{'source':<16}{'workload':<20}{'metric':<22}"
             f"{'n':>4}{'median':>10}{'latest':>10}  trend"]
    for (source, workload), recs in sorted(group_records(window).items()):
        verdicts = [r.get("verdict") for r in recs if r.get("verdict")]
        tag = ""
        if verdicts:
            n_fail = sum(1 for v in verdicts if v != "pass")
            tag = f"  [{len(verdicts) - n_fail}/{len(verdicts)} pass]"
        emitted = False
        for m in metrics:
            series = [float(r[m]) for r in recs
                      if isinstance(r.get(m), (int, float))]
            if not series:
                continue
            lines.append(
                f"{source:<16}{workload:<20.19}{m:<22}{len(series):>4}"
                f"{_human(_median(series)):>10}{_human(series[-1]):>10}"
                f"  {sparkline(series)}")
            emitted = True
        if emitted:
            if tag:
                lines[-1] += tag
        else:
            # metric-less group (multichip dry runs carry only verdicts,
            # warm records only compile counts): still one row, so the
            # group is visible and its tag never lands on another row
            lines.append(f"{source:<16}{workload:<20.19}{'-':<22}"
                         f"{len(recs):>4}{'-':>10}{'-':>10}{tag}")
    return "\n".join(lines)


def _human(v: float) -> str:
    for cut, suf in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= cut:
            return f"{v / cut:.2f}{suf}"
    return f"{v:.2f}"


def _resolve_record(window: Sequence[dict], sel: str) -> Optional[dict]:
    """`--diff A B` selector: an integer indexes the chronological window
    (negatives from the end), anything else picks the newest record whose
    source, workload, key, or git sha matches."""
    try:
        return window[int(sel)]
    except (ValueError, IndexError):
        pass
    for rec in reversed(window):
        if sel in (rec.get("source"), rec.get("workload"),
                   rec.get("key"), rec.get("git_sha")):
            return rec
    return None


def render_diff(window: Sequence[dict], a_sel: str, b_sel: str) -> str:
    a, b = _resolve_record(window, a_sel), _resolve_record(window, b_sel)
    if a is None or b is None:
        missing = a_sel if a is None else b_sel
        return f"perf --diff: no record matches {missing!r}"
    lines = [f"{'':<24}{_slug(a):>18}{_slug(b):>18}{'delta':>10}"]
    for m in ("reads_per_sec", "cell_updates_per_sec", "mfu", "occupancy",
              "compile_misses"):
        av, bv = a.get(m), b.get(m)
        lines.append(f"{m:<24}{_fmt(av):>18}{_fmt(bv):>18}"
                     f"{_delta(av, bv):>10}")
    for p in ("p50", "p95", "p99"):
        av = (a.get("read_wall_ms") or {}).get(p)
        bv = (b.get("read_wall_ms") or {}).get(p)
        lines.append(f"read_wall_ms.{p:<11}{_fmt(av):>18}{_fmt(bv):>18}"
                     f"{_delta(av, bv):>10}")
    return "\n".join(lines)


def _slug(rec: dict) -> str:
    s = f"{rec.get('source')}:{rec.get('workload') or '-'}"
    return s[-18:]


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v) if v is not None else "-"


def _delta(a, b) -> str:
    if not isinstance(a, (int, float)) or not isinstance(b, (int, float)) \
            or not a:
        return "-"
    return f"{(b - a) / abs(a) * 100:+.1f}%"
