"""`abpoa-tpu top` — a live terminal dashboard over the metrics exporter.

Reads the Prometheus textfile a concurrent run maintains (`--metrics
FILE`, atomic renames, so a frame is never torn) and renders the
operator's one-screen view: reads/s, cell-updates/s, MFU, the phase
split, breaker states, compile hits/misses, fault and fallback counters.
Plain-refresh rendering (ANSI home+clear per frame) — no curses
dependency, works over ssh and in CI transcripts; `--once` prints a
single frame and exits (the testable path).

    terminal 1:  abpoa-tpu -l lists.txt --metrics /tmp/abpoa.prom
    terminal 2:  abpoa-tpu top /tmp/abpoa.prom
"""
from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, Tuple

from . import metrics as M

BAR_W = 24


def _labeled(samples, family: str, label: str) -> Dict[str, float]:
    """{label-value: sample} for every sample of `family` keyed by one
    label name."""
    out: Dict[str, float] = {}
    for (name, labels), v in samples.items():
        if name == family:
            d = dict(labels)
            if label in d:
                out[d[label]] = v
    return out


def _total(samples, family: str) -> float:
    return sum(v for (name, _l), v in samples.items() if name == family)


def _bar(frac: float, width: int = BAR_W) -> str:
    n = int(round(max(0.0, min(1.0, frac)) * width))
    return "#" * n + "." * (width - n)


def _fmt_si(v: float) -> str:
    for div, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= div:
            return f"{v / div:.2f}{suf}"
    return f"{v:.0f}"


def render_frame(samples, types, path: str, age_s: float) -> str:
    """One dashboard frame from parsed exposition samples."""
    lines = []
    staleness = " [STALE]" if age_s > 10 else ""
    lines.append(f"abpoa-tpu top — {path}  (updated {age_s:.1f}s ago"
                 f"{staleness})")
    runs = _total(samples, "abpoa_runs_total")
    dev = next((dict(lb) for (n, lb) in samples
                if n == "abpoa_device_info"), None)
    devs = (f"  device {dev.get('platform', '?')} {dev.get('kind', '')}"
            .rstrip() if dev else "")
    batch = M.sample_value(samples, "abpoa_batch_sets")
    prog = ""
    if batch:
        done = M.sample_value(samples, "abpoa_batch_sets_done") or 0
        prog = (f"  batch {done:.0f}/{batch:.0f} sets "
                f"{_bar(done / batch, 12)}")
    lines.append(f"runs {runs:.0f}{devs}{prog}")
    lines.append("")

    # throughput block
    reads = _total(samples, "abpoa_reads_total")
    rps = M.sample_value(samples, "abpoa_reads_per_second") or 0.0
    q = {lbl: v for lbl, v in _labeled(
        samples, "abpoa_read_wall_seconds_quantile", "quantile").items()}
    lat = ""
    if q:
        lat = ("  wall ms  p50 {:.2f}  p95 {:.2f}  p99 {:.2f}".format(
            1e3 * q.get("0.5", 0), 1e3 * q.get("0.95", 0),
            1e3 * q.get("0.99", 0)))
    lines.append(f"reads    {_fmt_si(reads):>9} total  {rps:>9.1f}/s{lat}")
    cells = _total(samples, "abpoa_dp_cells_total")
    cups = M.sample_value(samples, "abpoa_cell_updates_per_second") or 0.0
    mfu = M.sample_value(samples, "abpoa_mfu_ratio")
    mfu_s = f"  MFU {100 * mfu:.3f}%" if mfu is not None else ""
    lines.append(f"dp       {_fmt_si(cells):>9} cells  "
                 f"{_fmt_si(cups):>8}/s CUPS{mfu_s}")

    # serve panel (present only when an `abpoa-tpu serve` process feeds
    # the exporter): admission state + per-status dispositions + request
    # latency quantiles
    statuses = _labeled(samples, "abpoa_serve_requests_total", "status")
    qdepth = M.sample_value(samples, "abpoa_serve_queue_depth")
    if statuses or qdepth is not None:
        inflight = M.sample_value(samples, "abpoa_serve_inflight") or 0
        disp = "  ".join(f"{k}={v:.0f}" for k, v in sorted(statuses.items()))
        lines.append(f"serve    queue {qdepth or 0:.0f}  inflight "
                     f"{inflight:.0f}  {disp}")
        sq = _labeled(samples, "abpoa_serve_request_seconds_quantile",
                      "quantile")
        if sq:
            lines.append("         req ms  p50 {:.2f}  p95 {:.2f}  "
                         "p99 {:.2f}".format(
                             1e3 * sq.get("0.5", 0), 1e3 * sq.get("0.95", 0),
                             1e3 * sq.get("0.99", 0)))

    # scheduler panel (present once a batch/serve route was planned):
    # the selected route, lockstep K cap, route-decision counts, and the
    # measured divergence EWMA the K-cap heuristic feeds on
    route_hot = _labeled(samples, "abpoa_scheduler_route", "route")
    # the route counter carries a `reason` label too (crossover vs
    # ineligible vs eligible...), so per-route display sums over reasons
    routes: Dict[str, float] = {}
    for (name, labels), v in samples.items():
        if name == "abpoa_scheduler_routes_total":
            r = dict(labels).get("route")
            if r is not None:
                routes[r] = routes.get(r, 0.0) + v
    if route_hot or routes:
        cur = next((k for k, v in route_hot.items() if v >= 1), "?")
        k_cap = M.sample_value(samples, "abpoa_scheduler_k_cap")
        noop = M.sample_value(samples, "abpoa_lockstep_noop_fraction")
        parts = [f"route {cur}"]
        if k_cap is not None:
            parts.append(f"k_cap {k_cap:.0f}")
        if noop is not None:
            parts.append(f"noop {noop:.2f} [{_bar(noop, 8)}]")
        if routes:
            parts.append("  ".join(f"{k}={v:.0f}"
                                   for k, v in sorted(routes.items())))
        lines.append("sched    " + "  ".join(parts))
        # mesh row (present only when the sharded route built a device
        # mesh): device count, platform, per-shard lane occupancy
        mesh_n = M.sample_value(samples, "abpoa_mesh_devices")
        if mesh_n:
            plat = next((dict(lb).get("platform", "?")
                         for (n, lb) in samples
                         if n == "abpoa_mesh_platform_info"), "?")
            shard_occ = _labeled(samples, "abpoa_shard_lane_occupancy",
                                 "shard")
            occ_s = ""
            if shard_occ:
                occ_s = "  occ " + " ".join(
                    f"{s}:{v:.2f}" for s, v in sorted(
                        shard_occ.items(), key=lambda kv: int(kv[0])))
            lines.append(f"         mesh {mesh_n:.0f}x{plat}{occ_s}")
            # shard-skew row (obs/rounds.py): max/min estimated shard
            # wall of the last sharded round + the straggler shard that
            # gated it — the round-12-straggler question, live
            skew = M.sample_value(samples, "abpoa_shard_skew_ratio")
            shard_walls = _labeled(samples,
                                   "abpoa_shard_round_wall_seconds",
                                   "shard")
            if skew is not None and shard_walls:
                straggler = M.sample_value(samples,
                                           "abpoa_shard_straggler")
                walls = sorted(shard_walls.items(),
                               key=lambda kv: kv[1])
                lo_s, lo_w = walls[0]
                hi_s, hi_w = walls[-1]
                lines.append(
                    f"         skew {skew:.2f}x  round wall "
                    f"max {1e3 * hi_w:.2f} ms (shard {hi_s}) / "
                    f"min {1e3 * lo_w:.2f} ms (shard {lo_s})  "
                    f"straggler shard "
                    f"{straggler if straggler is None else int(straggler)}")
        chunks = _total(samples, "abpoa_lockstep_chunks_total")
        drains = _total(samples, "abpoa_lockstep_drain_chunks_total")
        if chunks:
            lines.append(f"         lockstep rounds {chunks:.0f}  "
                         f"drain {drains:.0f}")
        # continuous batching: measured lane occupancy + churn counters
        # (joins boarded mid-flight, lanes retired early, boundary
        # evictions) and the join-wait quantiles
        occ = M.sample_value(samples, "abpoa_lockstep_lane_occupancy")
        if occ is not None:
            lines.append(f"         occupancy {occ:.2f} [{_bar(occ, 8)}]")
        joins = _total(samples, "abpoa_lockstep_joins_total")
        retires = _total(samples, "abpoa_lockstep_early_retires_total")
        evicts = _total(samples, "abpoa_lockstep_evictions_total")
        if joins or retires or evicts:
            lines.append(f"         churn joins {joins:.0f}  "
                         f"early-retires {retires:.0f}  "
                         f"evictions {evicts:.0f}")
        jq = _labeled(samples, "abpoa_lockstep_join_wait_seconds_quantile",
                      "quantile")
        if jq:
            lines.append("         join wait p50 %.0f ms  p99 %.0f ms"
                         % (1e3 * jq.get("0.5", 0), 1e3 * jq.get("0.99", 0)))

    # map panel (present only when a map workload ran: `abpoa-tpu map`
    # or serve --map-graph): pure-throughput reads against the static
    # graph, plus the zero-barrier lane occupancy and join counters
    map_reads = _total(samples, "abpoa_map_reads_total")
    if map_reads:
        mrps = M.sample_value(samples, "abpoa_map_reads_per_second") or 0.0
        parts = [f"{_fmt_si(map_reads):>9} reads  {mrps:>9.1f}/s"]
        rounds = _total(samples, "abpoa_map_rounds_total")
        if rounds:
            parts.append(f"rounds {rounds:.0f}")
        joins = _total(samples, "abpoa_map_joins_total")
        if joins:
            parts.append(f"joins {joins:.0f}")
        lines.append("map      " + "  ".join(parts))
        mocc = M.sample_value(samples, "abpoa_map_lane_occupancy")
        if mocc is not None:
            lines.append(f"         occupancy {mocc:.2f} [{_bar(mocc, 8)}]")

    # process-pool panel (present only when a supervised worker pool ran:
    # -l --workers N or serve --pool-workers N)
    pool_up = M.sample_value(samples, "abpoa_pool_workers")
    if pool_up is not None:
        parts = [f"workers {pool_up:.0f}"]
        for fam, lbl in (("abpoa_pool_restarts_total", "restarts"),
                         ("abpoa_pool_kills_total", "kills"),
                         ("abpoa_pool_requeues_total", "requeues"),
                         ("abpoa_pool_poison_jobs_total", "poison")):
            v = _total(samples, fam)
            if v:
                parts.append(f"{lbl} {v:.0f}")
        lines.append("pool     " + "  ".join(parts))

    # tracing panel (PR 15): per-request traces written + flight dumps
    # harvested — the postmortem feed `abpoa-tpu why` consumes
    traces = _total(samples, "abpoa_serve_traces_total")
    dumps = _total(samples, "abpoa_pool_flight_dumps_total")
    if traces or dumps:
        parts = []
        if traces:
            parts.append(f"request traces {traces:.0f}")
        if dumps:
            parts.append(f"flight dumps {dumps:.0f}")
        lines.append("tracing  " + "  ".join(parts))

    # abandoned watchdog threads leak IN-PROCESS dispatches only (inside
    # pool workers the supervisor's SIGKILL replaces abandonment), so the
    # readout must not hide behind the pool panel
    abandoned = M.sample_value(samples, "abpoa_watchdog_abandoned_threads")
    if abandoned:
        lines.append(f"watchdog abandoned-threads {abandoned:.0f}")

    # phase split
    phases = _labeled(samples, "abpoa_phase_wall_seconds_total", "phase")
    tot = sum(phases.values())
    if tot > 0:
        lines.append("")
        lines.append(f"phases   ({tot:.1f}s recorded)")
        for name, w in sorted(phases.items(), key=lambda kv: -kv[1])[:8]:
            frac = w / tot
            lines.append(f"  {name:<16} {_bar(frac)} {100 * frac:>5.1f}% "
                         f"{w:>8.2f}s")

    # compiles
    hits = _total(samples, "abpoa_compile_hits_total")
    misses = _total(samples, "abpoa_compile_misses_total")
    if hits or misses:
        xla = _total(samples, "abpoa_xla_compile_seconds_total")
        xla_s = f"  {xla:.1f}s in XLA" if xla else ""
        lines.append("")
        lines.append(f"compiles {misses:.0f} compiled / {hits:.0f} cache "
                     f"hits{xla_s}")

    # resilience block
    breakers = _labeled(samples, "abpoa_breaker_open", "backend")
    if breakers:
        states = "  ".join(
            f"{b}={'OPEN' if v else 'closed'}"
            for b, v in sorted(breakers.items()))
        lines.append(f"breakers {states}")
    faults = _labeled(samples, "abpoa_faults_total", "kind")
    if faults:
        lines.append("faults   " + "  ".join(
            f"{k}={v:.0f}" for k, v in sorted(faults.items())))
    fbs = _labeled(samples, "abpoa_fallbacks_total", "reason")
    if fbs:
        lines.append("fallback " + "  ".join(
            f"{k}={v:.0f}" for k, v in sorted(fbs.items())))
    extras = []
    for fam, lbl in (("abpoa_quarantined_sets_total", "quarantined sets"),
                     ("abpoa_watchdog_fires_total", "watchdog fires"),
                     ("abpoa_admission_demotions_total",
                      "admission demotions")):
        v = _total(samples, fam)
        if v:
            extras.append(f"{lbl} {v:.0f}")
    if extras:
        lines.append("events   " + "  ".join(extras))
    return "\n".join(lines) + "\n"


def _read_frame(path: str) -> Tuple[str, float]:
    with open(path) as fp:
        text = fp.read()
    age = time.time() - os.path.getmtime(path)
    return text, age


def _fetch_frame(url: str, timeout: float = 5.0) -> Tuple[str, float]:
    """Scrape a live /metrics endpoint (a serve replica's HTTP exporter,
    or the fleet router's merged exposition) — the no-filesystem-access
    path a fleet operator watches a remote router through. A fetched
    frame is by definition fresh (age 0)."""
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace"), 0.0


def top_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="abpoa-tpu top",
        description="live terminal dashboard over the --metrics exporter "
                    "file of a concurrent run, or a live /metrics "
                    "endpoint (--url)")
    ap.add_argument("file", nargs="?", default=M.default_textfile_path(),
                    help="exporter textfile to watch "
                         "[%(default)s]")
    ap.add_argument("--url", default=None, metavar="URL",
                    help="scrape a live endpoint instead of the textfile "
                         "(e.g. http://host:port/metrics — a serve "
                         "replica or the fleet router's merged "
                         "exposition)")
    ap.add_argument("-n", "--interval", type=float, default=1.0,
                    help="refresh interval seconds [%(default)s]")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no screen clearing)")
    args = ap.parse_args(argv)
    src = args.url or args.file
    while True:
        try:
            if args.url:
                text, age = _fetch_frame(args.url)
            else:
                text, age = _read_frame(args.file)
            samples, types = M.parse_exposition(text)
            frame = render_frame(samples, types, src, age)
        except OSError as e:
            if args.url:
                frame = (f"abpoa-tpu top — waiting for {args.url}\n"
                         f"({e})\n")
            else:
                frame = (f"abpoa-tpu top — waiting for {args.file}\n"
                         "(start a run with `--metrics "
                         f"{args.file}` to feed it)\n")
        except ValueError as e:
            frame = f"abpoa-tpu top — unparseable exposition: {e}\n"
        if args.once:
            sys.stdout.write(frame)
            return 0
        # plain refresh: home + clear-to-end, then the frame
        sys.stdout.write("\x1b[H\x1b[2J" + frame)
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
