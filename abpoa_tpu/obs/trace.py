"""Event-level span tracer -> Chrome trace-event JSON (Perfetto-viewable).

The RunReport (report.py) answers "where did the time go" in aggregate;
this module answers "when" — a hierarchical timeline of the same phase
names plus per-read / per-chunk / per-window / per-compile events, the
shared-timeline attribution SeGraM reports per stage (arXiv:2205.05883).
Armed by CLI `--trace FILE` (or `enable()` from the API), exported as the
Chrome trace-event format, which both Perfetto (ui.perfetto.dev) and
chrome://tracing load directly.

Overhead contract: disabled (the default) every hook is one attribute
check; enabled, a span is two `perf_counter()` calls and one ring-buffer
store — no device syncs, no allocation beyond the event tuple. The ring
buffer is bounded (default 65536 events): a pathological run overwrites
its oldest events and reports the drop count in the export metadata
instead of growing without bound. `RunReport.phase()` forwards its own
(t0, dt) measurements here, so phase spans and phase timers are the same
numbers by construction — the trace reconciles with the report exactly,
not just "within noise".

Concurrency: since PR 15 serve is a multi-threaded writer (dispatch
workers, watchdog threads, one pool supervisor thread per slot), so the
ring store, tid assignment and the request index run under one plain
Lock — a single uncontended acquire per event, the same cost class the
metrics registry accepted in PR 12 when serve became its first
concurrent publisher.

Request context (PR 15): every event optionally carries a request tag
``(rid, attempt)`` taken from a thread-local set by `request_ctx()` — the
id minted at serve ingress (or per `-l` set under `--workers`) rides every
span down to `dp:<backend>`/`compile:<fn>`, across the pool-worker pipe
(worker span deltas are re-added parent-side with `add_foreign`, rebased
onto the parent-observed dispatch time), and back out as ONE per-request
Chrome trace via `export_chrome_trace(..., events=events_for(rid))`.
Sampling (`ABPOA_TPU_TRACE_SAMPLE`, default 1.0) is deterministic on the
id, so the parent and every worker agree on whether a request is traced.
"""
from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import Iterator, Optional, Tuple

DEFAULT_CAPACITY = 65536

# event tuples: (kind, name, cat, t_start_s, dur_s, tid, args, req)
# kind: "X" complete span | "i" instant; req: None | (rid, attempt)
_KIND_SPAN = "X"
_KIND_INSTANT = "i"

# thread-local request context: (rid, attempt) tagged onto every event
_CTX = threading.local()

# installed flight recorder (obs/flight.py, pool workers): span() notifies
# it of entry/exit so a SIGKILLed worker's dump names the OPEN span — the
# one completed spans can never show, because the kill interrupts it
_FLIGHT = None


def new_request_id() -> str:
    """Mint a request id (12 hex chars) at ingress. Random, not
    sequential: ids from concurrent servers / restarted processes must
    not collide in a shared archive."""
    return os.urandom(6).hex()


def current_request() -> Optional[Tuple[str, int]]:
    return getattr(_CTX, "req", None)


@contextlib.contextmanager
def request_ctx(rid: Optional[str], attempt: int = 0) -> Iterator[None]:
    """Tag every event recorded by this thread with (rid, attempt) —
    the propagation primitive: serve workers wrap request execution,
    pool workers wrap job execution (attempt > 0 there, so a requeued
    request's two attempts stay distinct in the merged tree)."""
    if not rid:
        yield
        return
    prev = getattr(_CTX, "req", None)
    _CTX.req = (rid, int(attempt))
    try:
        yield
    finally:
        _CTX.req = prev


def sample_rate() -> float:
    try:
        return float(os.environ.get("ABPOA_TPU_TRACE_SAMPLE", "1") or 1.0)
    except ValueError:
        return 1.0


def sampled(rid: str) -> bool:
    """Deterministic per-request sampling decision: a hash of the id
    against ABPOA_TPU_TRACE_SAMPLE, so every process that sees the id
    (server, pool supervisor, worker) reaches the same verdict without
    coordination."""
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0 or not rid:
        return False
    try:
        return (int(rid, 16) % 10_000) < rate * 10_000
    except ValueError:
        return True


def set_flight(rec) -> None:
    """Install (or clear, with None) the flight recorder span() notifies."""
    global _FLIGHT
    _FLIGHT = rec


# per-request index bound: one pathological request cannot grow its
# slice without limit (the ring's own cap still governs the global view)
REQUEST_INDEX_CAP = 4096


class Tracer:
    """Bounded ring buffer of trace events on a monotonic clock."""

    __slots__ = ("enabled", "capacity", "t0", "_buf", "_n", "_tids",
                 "index_requests", "_req_idx", "_req_drop", "_lock")

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.enabled = False
        self.capacity = capacity
        # request indexing (serve --trace-dir): registered rids get their
        # events appended to a side list at store time, so a per-request
        # export is O(its own events) instead of a full-ring scan per
        # request (which would grow with server lifetime up to capacity)
        self.index_requests = False
        # serve threads write concurrently: ring counter/overwrite and
        # the request index must not race (a lost `_n` increment would
        # desync the rotation slice in events() permanently)
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        self.t0 = time.perf_counter()
        self._buf: list = []
        self._n = 0          # total events ever added (>= len(_buf))
        self._tids: dict = {}  # thread ident -> dense tid
        self._req_idx: dict = {}  # rid -> [events], registered rids only
        self._req_drop: dict = {}  # rid -> events cut at REQUEST_INDEX_CAP

    # ------------------------------------------------------------- recording
    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.get(ident)
                if tid is None:
                    tid = len(self._tids) + 1
                    self._tids[ident] = tid
        return tid

    def _store(self, ev: tuple) -> None:
        with self._lock:
            if self._n < self.capacity:
                self._buf.append(ev)
            else:
                self._buf[self._n % self.capacity] = ev  # overwrite oldest
            self._n += 1
            if self.index_requests and ev[7] is not None:
                rid = ev[7][0]
                lst = self._req_idx.get(rid)
                if lst is not None:
                    if len(lst) < REQUEST_INDEX_CAP:
                        lst.append(ev)
                    else:
                        # never silent: the cut is counted and shipped in
                        # the export metadata (events_since convention)
                        self._req_drop[rid] = self._req_drop.get(rid, 0) + 1

    # ------------------------------------------------- request indexing
    def begin_request(self, rid: str) -> None:
        """Register a rid for indexed collection. Must happen BEFORE the
        request becomes visible to dispatch workers (serve registers
        before try_admit), or a fast request could be accounted — and its
        slice taken — before registration, leaking the entry."""
        if self.index_requests and rid:
            with self._lock:
                self._req_idx[rid] = []

    def take_request(self, rid: str) -> Optional[Tuple[list, int]]:
        """Remove and return a registered rid's (indexed events, events
        cut at REQUEST_INDEX_CAP) — also the leak bound: every registered
        request's index entry is taken exactly once at account/rejection
        time."""
        with self._lock:
            lst = self._req_idx.pop(rid, None)
            dropped = self._req_drop.pop(rid, 0)
            return None if lst is None else (lst, dropped)

    def add_span(self, name: str, cat: str, t_start: float, dur: float,
                 args: Optional[dict] = None,
                 req: Optional[Tuple[str, int]] = None) -> None:
        """Record a completed span from caller-held timestamps (the path
        RunReport.phase uses, so span == timer to the last bit). `req`
        overrides the thread-local request tag (parent-side bookkeeping
        spans recorded on behalf of another thread's request)."""
        self._store((_KIND_SPAN, name, cat, t_start, dur, self._tid(),
                     args, req if req is not None else current_request()))

    def add_instant(self, name: str, cat: str,
                    args: Optional[dict] = None) -> None:
        self._store((_KIND_INSTANT, name, cat, time.perf_counter(), 0.0,
                     self._tid(), args, current_request()))

    def add_foreign(self, kind: str, name: str, cat: str, t_start: float,
                    dur: float, tid: int, args: Optional[dict],
                    req: Optional[Tuple[str, int]]) -> None:
        """Re-add an event measured in ANOTHER process (a pool worker's
        shipped span delta), already rebased onto this tracer's timeline;
        `tid` is the foreign worker's pid so the Chrome trace renders the
        pipe crossing as separate tracks."""
        self._store((kind, name, cat, t_start, dur, tid, args, req))

    # ------------------------------------------------------------- reading
    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    def events(self) -> list:
        """Events oldest-first (unwrapping the ring); a consistent
        snapshot under the writer lock."""
        with self._lock:
            if self._n <= self.capacity:
                return list(self._buf)
            k = self._n % self.capacity
            return self._buf[k:] + self._buf[:k]

    def tail(self, k: int) -> list:
        """The newest `k` events, oldest-first, WITHOUT unwrapping the
        whole ring — O(k) under the lock. The flight recorder reads this
        once per heartbeat; a full events() copy of a filled 65536-event
        ring per beat would stall concurrent span recording for the
        duration of the copy."""
        with self._lock:
            if self._n <= self.capacity:
                return self._buf[-k:]
            i = self._n % self.capacity   # oldest slot / wrap point
            if k <= i:
                return self._buf[i - k:i]
            return self._buf[-(k - i):] + self._buf[:i]

    def events_since(self, n0: int, cap: int = 2048) -> Tuple[list, int]:
        """(events recorded after total-count `n0`, dropped) — the
        per-job span delta a pool worker ships back with its result.
        Bounded at `cap` newest; overwritten/overflowed events count as
        dropped, never silently vanish."""
        new = self._n - n0
        if new <= 0:
            return [], 0
        evs = self.events()
        take = evs[-min(new, len(evs)):]
        dropped = new - len(take)
        if len(take) > cap:
            dropped += len(take) - cap
            take = take[-cap:]
        return take, dropped

    def events_for(self, rid: str) -> list:
        """Every ring event tagged with request id `rid`, oldest-first —
        the per-request slice export_chrome_trace turns into one
        Perfetto-viewable file."""
        return [e for e in self.events() if e[7] is not None
                and e[7][0] == rid]


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def enable(capacity: Optional[int] = None) -> None:
    """Arm tracing (resets the buffer and the timeline origin)."""
    if capacity:
        _TRACER.capacity = int(capacity)
    _TRACER.reset()
    _TRACER.enabled = True


def disable() -> None:
    _TRACER.enabled = False


def enabled() -> bool:
    return _TRACER.enabled


@contextlib.contextmanager
def span(name: str, cat: str = "run",
         args: Optional[dict] = None) -> Iterator[None]:
    """Timed hierarchical span; nesting is expressed by time containment
    (how the Chrome trace format builds its flame graph). Disabled: one
    attribute check and a bare yield. When a flight recorder is installed
    (pool workers), entry/exit are mirrored to its open-span stack so a
    hard kill mid-span is attributable from the harvested dump."""
    if not _TRACER.enabled:
        yield
        return
    fl = _FLIGHT
    t0 = time.perf_counter()
    if fl is not None:
        fl.push_open(name, cat, t0, args)
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        _TRACER.add_span(name, cat, t0, dt, args)
        if fl is not None:
            fl.pop_open(name, cat, t0, dt, args)


def instant(name: str, cat: str = "run", args: Optional[dict] = None) -> None:
    """Zero-duration marker (growth events, fallbacks, errors)."""
    if _TRACER.enabled:
        _TRACER.add_instant(name, cat, args)


def add_span(name: str, cat: str, t_start: float, dur: float,
             args: Optional[dict] = None,
             req: Optional[Tuple[str, int]] = None) -> None:
    """Record a span from caller-held timestamps (RunReport.phase)."""
    if _TRACER.enabled:
        _TRACER.add_span(name, cat, t_start, dur, args, req=req)


# --------------------------------------------------------------------------- #
# Chrome trace-event export                                                   #
# --------------------------------------------------------------------------- #

def to_chrome_trace(extra_meta: Optional[dict] = None,
                    events: Optional[list] = None) -> dict:
    """The trace as a Chrome trace-event JSON object: `ph:"X"` complete
    events with microsecond ts/dur on a run-relative timeline; metadata
    records process naming and the drop count. `events` narrows the
    export to a subset (the per-request slice from events_for); request
    tags render as `args.rid`/`args.attempt` so Perfetto's args panel
    (and `abpoa-tpu why`) can follow one request across threads and the
    worker-pipe boundary."""
    t = _TRACER
    pid = os.getpid()
    out = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": "abpoa-tpu"}},
    ]
    meta = {"dropped_events": t.dropped, "capacity": t.capacity}
    if extra_meta:
        meta.update(extra_meta)
    out.append({"name": "trace_meta", "ph": "M", "pid": pid, "tid": 0,
                "args": meta})
    t0 = t.t0
    for kind, name, cat, ts, dur, tid, args, req in (
            t.events() if events is None else events):
        ev = {"name": name, "cat": cat, "ph": kind,
              "ts": round((ts - t0) * 1e6, 3), "pid": pid, "tid": tid}
        if kind == _KIND_SPAN:
            ev["dur"] = round(dur * 1e6, 3)
        else:
            ev["s"] = "t"  # thread-scoped instant
        if args or req:
            a = dict(args) if args else {}
            if req:
                a["rid"] = req[0]
                if req[1]:
                    a["attempt"] = req[1]
            ev["args"] = a
        out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str, fp=None,
                        extra_meta: Optional[dict] = None,
                        events: Optional[list] = None) -> None:
    """`--trace FILE` sink ('-' = stdout, or `fp` when stdout is taken)."""
    text = json.dumps(to_chrome_trace(extra_meta, events=events))
    if path == "-":
        (fp or sys.stdout).write(text + "\n")
    else:
        with open(path, "w") as out:
            out.write(text + "\n")


# prune cadence: the directory listing is the expensive part of the
# bound, so it runs every 32 exports (the bound is then max_files + 32,
# still firmly bounded) — not on every request's latency path
_EXPORTS = {"n": 0}


def export_request_trace(dirpath: str, rid: str,
                         extra_meta: Optional[dict] = None,
                         max_files: Optional[int] = None,
                         events: Optional[list] = None) -> Optional[str]:
    """Write one request's span slice as `req-<rid>.trace.json` under
    `dirpath` (the serve `--trace-dir` sink). Bounded like the ring:
    past ABPOA_TPU_TRACE_DIR_MAX files (default 512) the oldest trace
    files are deleted. `events` short-circuits the ring scan (the serve
    path passes the request's indexed slice — O(its own events) per
    request instead of O(ring)). Returns the written path, or None when
    the request recorded no events / the directory is unwritable
    (tracing must never fail the request that produced it)."""
    evs = events if events is not None else _TRACER.events_for(rid)
    if not evs:
        return None
    if max_files is None:
        max_files = int(os.environ.get("ABPOA_TPU_TRACE_DIR_MAX", "512"))
    path = os.path.join(dirpath, f"req-{rid}.trace.json")
    try:
        os.makedirs(dirpath, exist_ok=True)
        meta = {"request_id": rid, "events": len(evs)}
        if extra_meta:
            meta.update(extra_meta)
        export_chrome_trace(path, extra_meta=meta, events=evs)
        _EXPORTS["n"] += 1
        if _EXPORTS["n"] % 32 == 0 or max_files < 32:
            _prune_trace_dir(dirpath, max_files)
    except OSError:
        return None
    return path


def _prune_trace_dir(dirpath: str, max_files: int) -> None:
    try:
        names = [n for n in os.listdir(dirpath)
                 if n.startswith("req-") and n.endswith(".trace.json")]
        if len(names) <= max_files:
            return
        full = sorted((os.path.getmtime(os.path.join(dirpath, n)), n)
                      for n in names)
        for _mt, n in full[:len(names) - max_files]:
            os.unlink(os.path.join(dirpath, n))
    except OSError:
        pass


def span_totals(cat: Optional[str] = None) -> dict:
    """Per-name wall sums over recorded spans (tests reconcile these with
    the RunReport phase timers)."""
    tot: dict = {}
    for kind, name, c, _ts, dur, _tid, _args, _req in _TRACER.events():
        if kind == _KIND_SPAN and (cat is None or c == cat):
            tot[name] = tot.get(name, 0.0) + dur
    return tot
