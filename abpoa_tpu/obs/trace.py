"""Event-level span tracer -> Chrome trace-event JSON (Perfetto-viewable).

The RunReport (report.py) answers "where did the time go" in aggregate;
this module answers "when" — a hierarchical timeline of the same phase
names plus per-read / per-chunk / per-window / per-compile events, the
shared-timeline attribution SeGraM reports per stage (arXiv:2205.05883).
Armed by CLI `--trace FILE` (or `enable()` from the API), exported as the
Chrome trace-event format, which both Perfetto (ui.perfetto.dev) and
chrome://tracing load directly.

Overhead contract: disabled (the default) every hook is one attribute
check; enabled, a span is two `perf_counter()` calls and one ring-buffer
store — no device syncs, no allocation beyond the event tuple. The ring
buffer is bounded (default 65536 events): a pathological run overwrites
its oldest events and reports the drop count in the export metadata
instead of growing without bound. `RunReport.phase()` forwards its own
(t0, dt) measurements here, so phase spans and phase timers are the same
numbers by construction — the trace reconciles with the report exactly,
not just "within noise".

Single-writer assumption: events append without a lock (CPython list ops
are atomic; the drivers are single-threaded). Multi-threaded writers
would only ever interleave events, never corrupt the buffer.
"""
from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import Iterator, Optional

DEFAULT_CAPACITY = 65536

# event tuples: (kind, name, cat, t_start_s, dur_s, tid, args)
# kind: "X" complete span | "i" instant
_KIND_SPAN = "X"
_KIND_INSTANT = "i"


class Tracer:
    """Bounded ring buffer of trace events on a monotonic clock."""

    __slots__ = ("enabled", "capacity", "t0", "_buf", "_n", "_tids")

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.enabled = False
        self.capacity = capacity
        self.reset()

    def reset(self) -> None:
        self.t0 = time.perf_counter()
        self._buf: list = []
        self._n = 0          # total events ever added (>= len(_buf))
        self._tids: dict = {}  # thread ident -> dense tid

    # ------------------------------------------------------------- recording
    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = len(self._tids) + 1
            self._tids[ident] = tid
        return tid

    def add_span(self, name: str, cat: str, t_start: float, dur: float,
                 args: Optional[dict] = None) -> None:
        """Record a completed span from caller-held timestamps (the path
        RunReport.phase uses, so span == timer to the last bit)."""
        ev = (_KIND_SPAN, name, cat, t_start, dur, self._tid(), args)
        if self._n < self.capacity:
            self._buf.append(ev)
        else:
            self._buf[self._n % self.capacity] = ev  # overwrite oldest
        self._n += 1

    def add_instant(self, name: str, cat: str,
                    args: Optional[dict] = None) -> None:
        ev = (_KIND_INSTANT, name, cat, time.perf_counter(), 0.0,
              self._tid(), args)
        if self._n < self.capacity:
            self._buf.append(ev)
        else:
            self._buf[self._n % self.capacity] = ev
        self._n += 1

    # ------------------------------------------------------------- reading
    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    def events(self) -> list:
        """Events oldest-first (unwrapping the ring)."""
        if self._n <= self.capacity:
            return list(self._buf)
        k = self._n % self.capacity
        return self._buf[k:] + self._buf[:k]


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def enable(capacity: Optional[int] = None) -> None:
    """Arm tracing (resets the buffer and the timeline origin)."""
    if capacity:
        _TRACER.capacity = int(capacity)
    _TRACER.reset()
    _TRACER.enabled = True


def disable() -> None:
    _TRACER.enabled = False


def enabled() -> bool:
    return _TRACER.enabled


@contextlib.contextmanager
def span(name: str, cat: str = "run",
         args: Optional[dict] = None) -> Iterator[None]:
    """Timed hierarchical span; nesting is expressed by time containment
    (how the Chrome trace format builds its flame graph). Disabled: one
    attribute check and a bare yield."""
    if not _TRACER.enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _TRACER.add_span(name, cat, t0, time.perf_counter() - t0, args)


def instant(name: str, cat: str = "run", args: Optional[dict] = None) -> None:
    """Zero-duration marker (growth events, fallbacks, errors)."""
    if _TRACER.enabled:
        _TRACER.add_instant(name, cat, args)


def add_span(name: str, cat: str, t_start: float, dur: float,
             args: Optional[dict] = None) -> None:
    """Record a span from caller-held timestamps (RunReport.phase)."""
    if _TRACER.enabled:
        _TRACER.add_span(name, cat, t_start, dur, args)


# --------------------------------------------------------------------------- #
# Chrome trace-event export                                                   #
# --------------------------------------------------------------------------- #

def to_chrome_trace(extra_meta: Optional[dict] = None) -> dict:
    """The trace as a Chrome trace-event JSON object: `ph:"X"` complete
    events with microsecond ts/dur on a run-relative timeline; metadata
    records process naming and the drop count."""
    t = _TRACER
    pid = os.getpid()
    out = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": "abpoa-tpu"}},
    ]
    meta = {"dropped_events": t.dropped, "capacity": t.capacity}
    if extra_meta:
        meta.update(extra_meta)
    out.append({"name": "trace_meta", "ph": "M", "pid": pid, "tid": 0,
                "args": meta})
    t0 = t.t0
    for kind, name, cat, ts, dur, tid, args in t.events():
        ev = {"name": name, "cat": cat, "ph": kind,
              "ts": round((ts - t0) * 1e6, 3), "pid": pid, "tid": tid}
        if kind == _KIND_SPAN:
            ev["dur"] = round(dur * 1e6, 3)
        else:
            ev["s"] = "t"  # thread-scoped instant
        if args:
            ev["args"] = args
        out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str, fp=None,
                        extra_meta: Optional[dict] = None) -> None:
    """`--trace FILE` sink ('-' = stdout, or `fp` when stdout is taken)."""
    text = json.dumps(to_chrome_trace(extra_meta))
    if path == "-":
        (fp or sys.stdout).write(text + "\n")
    else:
        with open(path, "w") as out:
            out.write(text + "\n")


def span_totals(cat: Optional[str] = None) -> dict:
    """Per-name wall sums over recorded spans (tests reconcile these with
    the RunReport phase timers)."""
    tot: dict = {}
    for kind, name, c, _ts, dur, _tid, _args in _TRACER.events():
        if kind == _KIND_SPAN and (cat is None or c == cat):
            tot[name] = tot.get(name, 0.0) + dur
    return tot
