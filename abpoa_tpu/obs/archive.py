"""Run-report archive: the cross-run memory behind `abpoa-tpu slo`.

Each CLI run appends one compact JSONL record (the SLO-relevant slice of
its RunReport — wall, read percentiles, fallback/recompile/fault counts)
to ``~/.cache/abpoa_tpu/reports/reports.jsonl``; `abpoa-tpu serve`
appends one record per REQUEST through the same `append_record`, so the
archive is what turns per-run telemetry into fleet questions: "what was
our fallback rate across the last 500 runs", "has warm p99 drifted this
week" — the sustained-workload reporting SeGraM / AnySeq-style
evaluations use instead of single cold runs.

Writers are concurrent: server worker threads append per-request records
while the flusher and CLI runs append theirs. Every record is therefore
written as ONE ``os.write`` on an ``O_APPEND`` descriptor — the kernel
serializes same-host appends, so lines can never interleave — and
rotation runs under a process lock (cross-thread) with a re-stat inside
it (cheap cross-process defense: at worst two processes rotate back to
back, which drops one generation early, never a torn line).

Growth is bounded: past ``ABPOA_TPU_ARCHIVE_MAX_MB`` (default 8 MB,
~20k records) the live file rotates to ``reports.jsonl.1`` (one rotated
generation kept), so a long-lived host caps at ~2x the limit.
``ABPOA_TPU_ARCHIVE=0`` disables archiving; ``ABPOA_TPU_ARCHIVE_DIR``
redirects it (CI smoke keeps its archive inside the workspace).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

ARCHIVE_FILE = "reports.jsonl"

# serializes rotation against in-process writers; the append itself needs
# no lock (single O_APPEND write)
_ROTATE_LOCK = threading.Lock()


def archive_enabled() -> bool:
    return os.environ.get("ABPOA_TPU_ARCHIVE", "1") not in ("0", "off")


def archive_dir() -> str:
    d = os.environ.get("ABPOA_TPU_ARCHIVE_DIR")
    if d:
        return d
    base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    return os.path.join(base, "abpoa_tpu", "reports")


def archive_path() -> str:
    return os.path.join(archive_dir(), ARCHIVE_FILE)


def max_bytes() -> int:
    return int(float(os.environ.get("ABPOA_TPU_ARCHIVE_MAX_MB", "8")) * 1e6)


def summarize_report(rep: dict, label: str = "",
                     device: str = "") -> dict:
    """One archive record from a finalized run report: the fields the SLO
    objectives evaluate, nothing that grows with the run."""
    reads = rep.get("reads") or {}
    comp = rep.get("compiles") or {}
    faults = rep.get("faults") or {}
    counters = rep.get("counters") or {}
    mfu = rep.get("mfu") or {}
    n_reads = reads.get("count") or 0
    total = rep.get("total_wall_s") or 0.0
    fallback_reads = sum((reads.get("fallbacks") or {}).values())
    return {
        "ts": rep.get("created") or time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                  time.gmtime()),
        "schema_version": rep.get("schema_version"),
        "label": label,
        "device": device,
        "total_wall_s": total,
        "reads": n_reads,
        "reads_per_sec": round(n_reads / total, 3) if total else None,
        "read_wall_ms": reads.get("wall_ms"),
        "fallback_reads": fallback_reads,
        "compile_hits": comp.get("hits", 0),
        "compile_misses": comp.get("misses", 0),
        "faults": faults.get("count", 0),
        "quarantined": counters.get("quarantine.sets", 0),
        "degraded": sorted(rep.get("degraded") or {}),
        "dp_cells": counters.get("dp.cells", 0),
        "cell_updates_per_sec": mfu.get("cell_updates_per_sec"),
        "mfu": mfu.get("mfu"),
    }


def append_record(rec: dict) -> Optional[str]:
    """Append one archive record (any dict with the summarize_report /
    serve-request field shapes). Thread- and process-safe: the line lands
    as a single O_APPEND write, so concurrent appenders can never
    interleave bytes mid-record. Returns the archive path (None when
    archiving is disabled or the directory is unwritable — archive
    failure must never fail the work that produced the record)."""
    if not archive_enabled():
        return None
    path = archive_path()
    data = (json.dumps(rec) + "\n").encode()
    try:
        os.makedirs(archive_dir(), exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
        _rotate_if_needed(path)
    except OSError:
        return None
    return path


def append_report(rep: dict, label: str = "", device: str = "") -> Optional[str]:
    """Archive one finalized run report (the CLI's per-run record)."""
    if not archive_enabled():
        return None
    return append_record(summarize_report(rep, label=label, device=device))


def _rotate_if_needed(path: str) -> None:
    # the lock serializes in-process rotations (server threads); the
    # re-stat inside it means only the first thread past the limit
    # rotates — late arrivals see the fresh small file and return
    with _ROTATE_LOCK:
        try:
            if os.path.getsize(path) <= max_bytes():
                return
            os.replace(path, path + ".1")  # drops any previous .1
        except OSError:
            pass


def fleet_dirs(base: Optional[str] = None) -> List[str]:
    """Per-replica archive dirs under a fleet base: the supervisor gives
    replica i its own ``<base>/replica-rI`` via ABPOA_TPU_ARCHIVE_DIR, so
    replica archives never interleave. Falls back to [base] itself when
    no replica subdirs exist — `slo --fleet` / `why --fleet` over a
    single-process archive degrade to the non-fleet behavior."""
    base = base or archive_dir()
    try:
        subs = sorted(os.path.join(base, d) for d in os.listdir(base)
                      if d.startswith("replica-")
                      and os.path.isdir(os.path.join(base, d)))
    except OSError:
        subs = []
    return subs or [base]


def read_fleet_window(n: int, base: Optional[str] = None) -> List[dict]:
    """The newest `n` records across every replica archive, merged in
    timestamp order — the `slo --fleet` evaluation window."""
    out: List[dict] = []
    for d in fleet_dirs(base):
        out.extend(read_window(n, path=os.path.join(d, ARCHIVE_FILE)))
    out.sort(key=lambda r: r.get("ts") or "")
    return out[-n:] if n else out


def find_request_fleet(rid: str, window: int = 0,
                       base: Optional[str] = None) -> List[dict]:
    """ALL records carrying request id `rid` across replica archives —
    a failed-over or hedged request leaves one record per delivery
    attempt, each in its own replica's archive. Ordered by attempt then
    timestamp so `why` can narrate the hop."""
    hits: List[dict] = []
    for d in fleet_dirs(base):
        for rec in read_window(window, path=os.path.join(d, ARCHIVE_FILE)):
            if rec.get("request_id") == rid or rec.get("label") == rid:
                hits.append(rec)
    hits.sort(key=lambda r: (r.get("attempt") or 1, r.get("ts") or ""))
    return hits


def find_request(rid: str, window: int = 0,
                 path: Optional[str] = None) -> Optional[dict]:
    """Newest archive record carrying request id `rid` (serve requests
    and pool jobs record one per terminal status, PR 15; the record's
    `trace_file`/`dump_file` fields point at the request's per-request
    Chrome trace and harvested flight dump). `abpoa-tpu why` resolves
    ids through here; `label` matches too so `req-N` labels from older
    logs still resolve."""
    for rec in reversed(read_window(window, path=path)):
        if rec.get("request_id") == rid or rec.get("label") == rid:
            return rec
    return None


def read_window(n: int, path: Optional[str] = None) -> List[dict]:
    """The newest `n` archive records, oldest-first (rotated generation
    included so a window survives a rotation boundary). Unparseable lines
    (a crash mid-append) are skipped, never fatal."""
    path = path or archive_path()
    lines: List[str] = []
    for p in (path + ".1", path):
        try:
            with open(p) as fp:
                lines.extend(fp.read().splitlines())
        except OSError:
            continue
    out: List[dict] = []
    for line in lines[-n:] if n else lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out
