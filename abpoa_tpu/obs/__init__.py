"""Run-telemetry subsystem: structured phase timers, counters, JSON run
reports (versioned schema), an MFU model, and on-chip profiler capture
hooks. See report.py for the schema, mfu.py for the model's assumptions,
capture.py for the `--profile-dir` hooks; README "Run telemetry" and
PERF.md document the consumer side (bench.py, chip_watcher)."""
from .capture import device_capture, profile_dir, set_profile_dir
from .report import (SCHEMA, SCHEMA_KEYS, SCHEMA_VERSION, RunReport, count,
                     finalize_report, observe, phase, record_dp, report,
                     set_enabled, start_run, summary, write_report)

__all__ = [
    "SCHEMA", "SCHEMA_KEYS", "SCHEMA_VERSION", "RunReport",
    "count", "observe", "phase", "record_dp", "report",
    "start_run", "set_enabled", "finalize_report", "write_report", "summary",
    "device_capture", "profile_dir", "set_profile_dir",
]
