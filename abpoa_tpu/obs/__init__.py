"""Run-telemetry subsystem: structured phase timers, counters, JSON run
reports (versioned schema), an MFU model, per-read tail-latency records,
a hierarchical span tracer (Chrome trace-event export, Perfetto-viewable),
a compile log for the jitted entry points, on-chip profiler capture
hooks — and, above the per-run layer, the fleet-grade metric registry
(metrics.py: streaming-quantile sketches, Prometheus exposition), the
cross-run report archive (archive.py) and SLO/error-budget evaluation
(slo.py, `abpoa-tpu slo`) plus the live `abpoa-tpu top` dashboard
(top.py) — and, since PR 15, cross-process request tracing (trace.py
request context + per-request export), the pool-worker flight recorder
(flight.py) and the `abpoa-tpu why` postmortem analyzer (why.py).
See report.py for the schema, trace.py for the timeline
contract, compile_log.py for compile detection, mfu.py for the model's
assumptions, capture.py for the `--profile-dir` hooks; README
"Run telemetry" / "Metrics & SLOs" / "Observability" and PERF.md
document the consumer side (bench.py, perf_gate, chip_watcher, CI
metrics-smoke / serve-smoke)."""
from . import archive, flight, ledger, metrics, rounds, trace
from .capture import device_capture, profile_dir, set_profile_dir
from .compile_log import compile_watch
from .report import (SCHEMA, SCHEMA_KEYS, SCHEMA_VERSION, RunReport, count,
                     finalize_report, observe, phase, record_dp, record_fault,
                     record_read, render_report, render_report_diff, report,
                     set_enabled, start_run, summary, write_report)
from .trace import (export_chrome_trace, export_request_trace, instant,
                    new_request_id, request_ctx, sampled, span, span_totals,
                    tracer)
from .trace import disable as trace_disable
from .trace import enable as trace_enable
from .trace import enabled as trace_enabled

__all__ = [
    "SCHEMA", "SCHEMA_KEYS", "SCHEMA_VERSION", "RunReport",
    "count", "observe", "phase", "record_dp", "record_fault", "record_read",
    "report",
    "start_run", "set_enabled", "finalize_report", "write_report", "summary",
    "render_report", "render_report_diff",
    "device_capture", "profile_dir", "set_profile_dir",
    "trace", "trace_enable", "trace_disable", "trace_enabled",
    "span", "instant", "span_totals", "export_chrome_trace", "tracer",
    "new_request_id", "request_ctx", "sampled", "export_request_trace",
    "compile_watch",
    "archive", "flight", "ledger", "metrics", "rounds",
]
