"""Run-telemetry subsystem: structured phase timers, counters, JSON run
reports (versioned schema), an MFU model, per-read tail-latency records,
a hierarchical span tracer (Chrome trace-event export, Perfetto-viewable),
a compile log for the jitted entry points, and on-chip profiler capture
hooks. See report.py for the schema, trace.py for the timeline contract,
compile_log.py for compile detection, mfu.py for the model's assumptions,
capture.py for the `--profile-dir` hooks; README "Run telemetry" and
PERF.md document the consumer side (bench.py, perf_gate, chip_watcher)."""
from . import trace
from .capture import device_capture, profile_dir, set_profile_dir
from .compile_log import compile_watch
from .report import (SCHEMA, SCHEMA_KEYS, SCHEMA_VERSION, RunReport, count,
                     finalize_report, observe, phase, record_dp, record_fault,
                     record_read, report, set_enabled, start_run, summary,
                     write_report)
from .trace import (export_chrome_trace, instant, span, span_totals, tracer)
from .trace import disable as trace_disable
from .trace import enable as trace_enable
from .trace import enabled as trace_enabled

__all__ = [
    "SCHEMA", "SCHEMA_KEYS", "SCHEMA_VERSION", "RunReport",
    "count", "observe", "phase", "record_dp", "record_fault", "record_read",
    "report",
    "start_run", "set_enabled", "finalize_report", "write_report", "summary",
    "device_capture", "profile_dir", "set_profile_dir",
    "trace", "trace_enable", "trace_disable", "trace_enabled",
    "span", "instant", "span_totals", "export_chrome_trace", "tracer",
    "compile_watch",
]
