"""`abpoa-tpu perf` — render the performance-trajectory ledger and run
the drift gate.

Default: a per-(source, workload) x metric table with count, median,
latest, and a sparkline of the series — the "has reads/s drifted over
the last N runs" answer the single overwritable baselines never gave.

`--diff A B` compares two records (integer window indexes, or the newest
record matching a source/workload/key/git-sha string). `--json` emits
the raw window for scripting.

`--gate` is the drift detector that replaces single-baseline staleness:
the NEWEST record of every (source, workload) group is compared against
the trailing-window MEDIAN of its own group; any metric below
`--threshold` x median fails (rc 1). Groups with fewer than
`--min-history` prior records pass vacuously — a brand-new workload must
not fail its own first runs. `--inject-slowdown F` divides the current
values first, the same self-test contract every tools/*_gate.py carries;
CI runs the flip to prove the gate can actually fail.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import ledger


def perf_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="abpoa-tpu perf",
        description="Render the performance-trajectory ledger "
                    "(PERF_LEDGER.jsonl) or run the drift gate.")
    ap.add_argument("--ledger", metavar="PATH", default=None,
                    help="ledger file (default: ABPOA_TPU_LEDGER_DIR/"
                         "PERF_LEDGER.jsonl)")
    ap.add_argument("--window", type=int, default=500, metavar="N",
                    help="newest N records to consider (default 500)")
    ap.add_argument("--json", action="store_true",
                    help="emit the record window (or gate verdicts) as "
                         "JSON instead of the table")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"),
                    help="compare two records: window indexes or "
                         "source/workload/key/sha selectors")
    ap.add_argument("--gate", action="store_true",
                    help="drift-gate mode: rc 1 when any (source, "
                         "workload) group's newest record regresses "
                         "below threshold x trailing median")
    ap.add_argument("--threshold", type=float, default=ledger.DRIFT_RATIO,
                    help="gate floor as a fraction of the trailing "
                         f"median (default {ledger.DRIFT_RATIO})")
    ap.add_argument("--min-history", type=int,
                    default=ledger.DRIFT_MIN_HISTORY,
                    help="prior records a group needs before it can "
                         f"fail (default {ledger.DRIFT_MIN_HISTORY})")
    ap.add_argument("--span", type=int, default=ledger.DRIFT_SPAN,
                    help="trailing records the median is taken over "
                         f"(default {ledger.DRIFT_SPAN})")
    ap.add_argument("--inject-slowdown", type=float, default=1.0,
                    metavar="F",
                    help="self-test: divide current metrics by F before "
                         "gating (the gate must flip to rc 1)")
    ap.add_argument("--metric", action="append", default=None,
                    help="metric(s) to render/gate (repeatable; default "
                         + "/".join(ledger.DRIFT_METRICS) + ")")
    args = ap.parse_args(argv)

    window = ledger.read_window(args.window, path=args.ledger)
    metrics = tuple(args.metric) if args.metric else ledger.DRIFT_METRICS

    if args.gate:
        return _gate(window, args, metrics)
    try:
        if args.diff:
            print(ledger.render_diff(window, args.diff[0], args.diff[1]))
        elif args.json:
            print(json.dumps(window))
        else:
            print(ledger.render_trajectory(window, metrics=metrics))
    except BrokenPipeError:
        # `perf | head` closing the pipe is not an error
        sys.stderr.close()
    return 0


def _gate(window, args, metrics) -> int:
    if not window:
        print("[perf-drift] FAIL: ledger is empty — run "
              "tools/ledger_backfill.py or any gate first",
              file=sys.stderr)
        return 1
    verdicts = ledger.drift_check(
        window, metrics=metrics, ratio=args.threshold,
        min_history=args.min_history, span=args.span,
        slowdown=args.inject_slowdown)
    if args.json:
        print(json.dumps(verdicts))
    bad = [v for v in verdicts if not v["ok"]]
    for v in verdicts:
        tag = "ok  " if v["ok"] else "DRIFT"
        med = v.get("median")
        print(f"[perf-drift] {tag} {v['source']}:{v['workload'] or '-'} "
              f"{v['metric']} current={v['current']} "
              f"median={med if med is not None else '-'} "
              f"n={v['n_history']}"
              + (f" floor={v['floor']}" if "floor" in v else "")
              + (f" ({v['note']})" if v.get("note") else ""),
              file=sys.stderr)
    if bad:
        print(f"[perf-drift] FAIL: {len(bad)} metric(s) regressed below "
              f"{args.threshold} x trailing median", file=sys.stderr)
        return 1
    print(f"[perf-drift] PASS: {len(verdicts)} metric checks over "
          f"{len(window)} records", file=sys.stderr)
    return 0
