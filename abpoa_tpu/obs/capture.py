"""On-chip capture hooks: jax.profiler/XProf traces around device dispatches.

`--profile-dir DIR` arms a process-global profile directory; the fused-loop
drivers then bracket their dispatch region with `device_capture(label)`,
which starts ONE `jax.profiler` trace for the outermost region (nested
regions reuse it via TraceAnnotation) and stops it on exit. The resulting
trace opens in XProf/TensorBoard and attributes per-step device time to the
annotated regions — the artifact the first alive TPU window needs.

Everything is a no-op when no profile dir is set (the default), when jax is
missing, or when the profiler refuses to start — a failed capture must
never take down an alignment run.
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

_PROFILE_DIR: Optional[str] = None
_ACTIVE = False  # a jax trace is running (jax allows only one at a time)


def set_profile_dir(path: Optional[str]) -> None:
    global _PROFILE_DIR
    if path:
        os.makedirs(path, exist_ok=True)
    _PROFILE_DIR = path or None


def profile_dir() -> Optional[str]:
    return _PROFILE_DIR


@contextlib.contextmanager
def device_capture(label: str) -> Iterator[None]:
    """Trace-capture bracket for a device dispatch region.

    Outermost call starts/stops the jax.profiler trace into the armed
    directory; inner calls (and all calls when unarmed) degrade to a plain
    TraceAnnotation / no-op."""
    global _ACTIVE
    d = _PROFILE_DIR
    if d is None:
        yield
        return
    try:
        import jax
    except Exception:
        yield
        return
    started = False
    if not _ACTIVE:
        try:
            jax.profiler.start_trace(d)
            started = True
            _ACTIVE = True
        except Exception:
            started = False
    # enter/exit the annotation defensively: a profiler hiccup must leave
    # the workload running un-annotated, and the generator must yield
    # exactly once on every path
    ann = None
    try:
        ann = jax.profiler.TraceAnnotation(label)
        ann.__enter__()
    except Exception:
        ann = None
    try:
        yield
    finally:
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        if started:
            _ACTIVE = False
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
