"""Structured run telemetry: the process-global `RunReport`.

The stderr verbosity ladder (utils/logging.py) answers "what happened";
this module answers "where did the time go" in machine-readable form —
the per-phase / per-counter attribution accelerated-alignment papers
report (SeGraM's per-stage cycle breakdowns, arXiv:2205.05883; AnySeq/GPU's
cell-updates-per-second per kernel stage, arXiv:2205.07610). One global
report per run, reset by `start_run()`, rendered by `finalize_report()`
into a versioned JSON schema (SCHEMA/SCHEMA_VERSION below).

Overhead contract: every hook is host-side aggregation of values the
pipeline already holds (dict increments, two `perf_counter()` calls per
phase enter/exit). Nothing here adds device syncs to the hot loop;
tests/test_obs.py guards warm-run wall with reporting on vs off.
"""
from __future__ import annotations

import contextlib
import json
import math
import sys
import time
from typing import Dict, Iterator, Optional

from . import compile_log as _clog
from . import trace as _trace

SCHEMA = "abpoa-tpu-run-report"
SCHEMA_VERSION = 3

# top-level keys of the rendered report, in schema order. Goldened by
# tests/test_obs.py: adding a key is a SCHEMA_VERSION bump.
# v2 adds `reads` (per-read latency records -> p50/p95/p99, the item-1
# service's SLO numbers) and `compiles` (the compile log, compile_log.py).
# v3 adds `faults` (every absorbed dispatch failure / quarantined set,
# abpoa_tpu/resilience) and `degraded` (circuit-breaker demotions active
# at the end of the run) — a clean run carries null for both.
SCHEMA_KEYS = ("schema", "schema_version", "created", "total_wall_s",
               "phase_wall_sum_s", "phases", "counters", "values",
               "reads", "compiles", "faults", "degraded", "device", "mfu")

# per-read record bound: percentiles over a truncated stream would lie,
# so past the cap records are dropped AND counted (`reads.dropped`)
READS_CAP = 100_000

# fault-record bound (same contract as READS_CAP): a fault storm must not
# grow the report without bound, but the drops are counted
FAULTS_CAP = 256


class RunReport:
    """Phase timers + counters + value summaries for one run."""

    __slots__ = ("enabled", "t_start", "phases", "counters", "values",
                 "reads", "reads_dropped", "faults", "faults_dropped",
                 "degraded")

    def __init__(self) -> None:
        self.enabled = True
        self.reset()

    def reset(self) -> None:
        self.t_start = time.perf_counter()
        self.phases: Dict[str, list] = {}    # name -> [wall_s, calls]
        self.counters: Dict[str, int] = {}   # name -> int
        self.values: Dict[str, list] = {}    # name -> [count, sum, min, max]
        # (wall_s, qlen, band_cols, backend, fallback, amortized)
        self.reads: list = []
        self.reads_dropped = 0
        # absorbed failures (resilience layer): dicts, FAULTS_CAP-bounded
        self.faults: list = []
        self.faults_dropped = 0
        # backend -> {"to", "reason", "failures"} (circuit-breaker opens)
        self.degraded: Dict[str, dict] = {}
        _clog.reset_run()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Accumulating wall-clock timer; re-entries add up. Phases are
        non-overlapping by convention (pipeline.py) so their sum is a
        partition of run wall time. The same (t0, dt) measurement feeds
        the trace timeline, so phase spans reconcile with phase timers
        exactly."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            rec = self.phases.get(name)
            if rec is None:
                self.phases[name] = [dt, 1]
            else:
                rec[0] += dt
                rec[1] += 1
            _trace.add_span(name, "phase", t0, dt)

    def count(self, name: str, n: int = 1) -> None:
        if self.enabled:
            self.counters[name] = self.counters.get(name, 0) + n

    def observe(self, name: str, value: float) -> None:
        """Value summary (count/sum/min/max) — a histogram's moments without
        bucket bookkeeping in the hot path."""
        if not self.enabled:
            return
        rec = self.values.get(name)
        if rec is None:
            self.values[name] = [1, value, value, value]
        else:
            rec[0] += 1
            rec[1] += value
            if value < rec[2]:
                rec[2] = value
            if value > rec[3]:
                rec[3] = value

    def record_dp(self, rows: int, band_cols: int, gap_mode: int) -> None:
        """Account one DP dispatch: band extent and cell totals, so reads/s
        can be normalized to cell-updates/s (the AnySeq/GPU metric). Values
        come from host-side planning state (graph row count, band formula)
        — never from a device readback."""
        self.record_dp_cells(rows * band_cols, 1, band_cols, gap_mode)

    def record_dp_cells(self, cells: int, dispatches: int, band_cols: int,
                        gap_mode: int) -> None:
        """Pre-aggregated DP accounting (the fused loop reports its whole
        run at once from a host-side model). Single owner of the dp.*
        counter schema."""
        if not self.enabled:
            return
        from .mfu import CELL_INT_OPS
        self.observe("dp.band_width", band_cols)
        self.count("dp.dispatches", dispatches)
        self.count("dp.cells", cells)
        self.count("dp.cell_ops", cells * CELL_INT_OPS.get(gap_mode, 16))

    def record_read(self, wall_s: float, qlen: int, band_cols: int,
                    backend: str, fallback: Optional[str] = None,
                    amortized: bool = False) -> None:
        """One per-read latency record (the SLO stream): wall seconds, read
        length, planned band extent, the backend that ran it, and the
        fallback reason when a faster path was bypassed. `amortized` marks
        records derived from a multi-read dispatch (fused loop / lockstep
        batch) whose wall was split evenly across its reads — the per-read
        number is then a share, not an independent measurement."""
        if not self.enabled:
            return
        if len(self.reads) < READS_CAP:
            self.reads.append((wall_s, qlen, band_cols, backend, fallback,
                               amortized))
        else:
            self.reads_dropped += 1

    def record_fault(self, kind: str, backend: Optional[str] = None,
                     set_index: Optional[int] = None, detail: str = "",
                     action: str = "") -> None:
        """One absorbed failure (abpoa_tpu/resilience): what failed, where
        it was headed, and what the degradation ladder did about it. The
        contract of that layer is that NOTHING is swallowed silently —
        every fallback/demotion/quarantine lands here (and in the
        `faults.<kind>` counter) even when the run then succeeds."""
        if not self.enabled:
            return
        self.count(f"faults.{kind}")
        if len(self.faults) >= FAULTS_CAP:
            self.faults_dropped += 1
            return
        rec = {"kind": kind, "t_s": round(time.perf_counter() - self.t_start,
                                          4)}
        if backend:
            rec["backend"] = backend
        if set_index is not None:
            rec["set"] = set_index
        if detail:
            rec["detail"] = detail
        if action:
            rec["action"] = action
        self.faults.append(rec)

    def mark_degraded(self, backend: str, to: str, reason: str,
                      failures: int) -> None:
        """A circuit-breaker open: `backend` serves as `to` for the rest
        of the run (resilience/breaker.py is the single caller)."""
        if self.enabled:
            self.degraded[backend] = {"to": to, "reason": reason,
                                      "failures": failures}

    # ----------------------------------------------------------- rendering
    def _faults_block(self) -> Optional[dict]:
        if not self.faults and not self.faults_dropped:
            return None
        kinds: Dict[str, int] = {}
        for rec in self.faults:
            kinds[rec["kind"]] = kinds.get(rec["kind"], 0) + 1
        return {
            "count": len(self.faults) + self.faults_dropped,
            "dropped": self.faults_dropped,
            "kinds": dict(sorted(kinds.items())),
            "records": self.faults,
        }

    def _reads_block(self) -> Optional[dict]:
        """Tail-latency aggregation of the per-read records: nearest-rank
        p50/p95/p99 over wall, plus backend/fallback attribution."""
        if not self.reads and not self.reads_dropped:
            return None
        walls = sorted(r[0] for r in self.reads)
        qlens = [r[1] for r in self.reads]
        bands = [r[2] for r in self.reads]
        backends: Dict[str, int] = {}
        fallbacks: Dict[str, int] = {}
        amortized = 0
        for _w, _q, _b, backend, fb, am in self.reads:
            backends[backend] = backends.get(backend, 0) + 1
            if fb:
                fallbacks[fb] = fallbacks.get(fb, 0) + 1
            if am:
                amortized += 1
        n = len(walls)

        def ms(x):
            return round(x * 1e3, 4)

        return {
            "count": n,
            "dropped": self.reads_dropped,
            "amortized": amortized,
            "backends": dict(sorted(backends.items())),
            "fallbacks": dict(sorted(fallbacks.items())),
            "wall_ms": {
                "p50": ms(_percentile(walls, 0.50)),
                "p95": ms(_percentile(walls, 0.95)),
                "p99": ms(_percentile(walls, 0.99)),
                "mean": ms(sum(walls) / n) if n else None,
                "max": ms(walls[-1]) if n else None,
            },
            "qlen": {"min": min(qlens), "max": max(qlens),
                     "mean": round(sum(qlens) / n, 1)} if n else None,
            "band_cols": {"min": min(bands), "max": max(bands)} if n else None,
        }

    @staticmethod
    def _compiles_block() -> Optional[dict]:
        """The run's compile log (compile_log.py): per-dispatch records for
        the jitted entry points, with XLA compile seconds and persistent-
        cache verdicts when the monitoring events fired."""
        recs = _clog.run_records()
        dropped = _clog.run_dropped()
        if not recs and not dropped:
            return None
        misses = sum(1 for r in recs if not r["cache_hit"])
        xla = sum(r.get("xla_compile_s") or 0.0 for r in recs)
        return {
            "count": len(recs) + dropped,
            "dropped": dropped,
            "misses": misses,
            "hits": len(recs) - misses,
            "xla_compile_s": round(xla, 6),
            "records": recs,
        }

    def as_dict(self) -> dict:
        from .mfu import mfu_block
        total = time.perf_counter() - self.t_start
        phases = {k: {"wall_s": round(v[0], 6), "calls": v[1]}
                  for k, v in sorted(self.phases.items())}
        values = {k: {"count": v[0], "sum": v[1], "min": v[2], "max": v[3]}
                  for k, v in sorted(self.values.items())}
        dev = _device_info()
        rep = {
            "schema": SCHEMA,
            "schema_version": SCHEMA_VERSION,
            "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "total_wall_s": round(total, 6),
            "phase_wall_sum_s": round(sum(v[0] for v in self.phases.values()),
                                      6),
            "phases": phases,
            "counters": dict(sorted(self.counters.items())),
            "values": values,
            "reads": self._reads_block(),
            "compiles": self._compiles_block(),
            "faults": self._faults_block(),
            "degraded": dict(sorted(self.degraded.items())) or None,
            "device": dev,
            "mfu": mfu_block(self, dev),
        }
        return rep


def _percentile(sorted_vals, q: float):
    """Nearest-rank percentile over an ascending list (no interpolation:
    a reported p99 is a latency some real read actually paid)."""
    if not sorted_vals:
        return None
    i = max(0, min(len(sorted_vals) - 1,
                   math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[i]


def _device_info() -> Optional[dict]:
    """Accelerator identity, host-side only: queried exclusively when jax is
    already imported (a device path ran), so a native/numpy run never pays a
    jax import — and never risks a wedged-tunnel hang — for its report."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        d = jax.devices()[0]
        return {"backend": "jax", "platform": str(d.platform),
                "kind": str(getattr(d, "device_kind", "") or "")}
    except Exception:
        return None


# --------------------------------------------------------------------------- #
# process-global registry                                                     #
# --------------------------------------------------------------------------- #

_REPORT = RunReport()


def report() -> RunReport:
    return _REPORT


def start_run() -> None:
    """Reset the global report; call at the top of each CLI/pyapi run."""
    _REPORT.reset()
    # backend-resolution state is process-global too; a new run must not
    # inherit the previous run's resolved kernel as a telemetry label
    try:
        from ..align.dispatch import _LAST_RESOLVED
        _LAST_RESOLVED["name"] = ""
        _LAST_RESOLVED["reason"] = None
    except Exception:
        pass
    # circuit-breaker demotions are run-scoped ("for the remainder of the
    # run"): a fresh run gets the requested backend back
    try:
        from ..resilience.breaker import breaker
        breaker().reset()
    except Exception:
        pass


def set_enabled(flag: bool) -> None:
    """Telemetry kill switch (the overhead-guard test's control arm)."""
    _REPORT.enabled = bool(flag)


def phase(name: str):
    return _REPORT.phase(name)


def count(name: str, n: int = 1) -> None:
    _REPORT.count(name, n)


def observe(name: str, value: float) -> None:
    _REPORT.observe(name, value)


def record_dp(rows: int, band_cols: int, gap_mode: int) -> None:
    _REPORT.record_dp(rows, band_cols, gap_mode)


def record_read(wall_s: float, qlen: int, band_cols: int, backend: str,
                fallback: Optional[str] = None,
                amortized: bool = False) -> None:
    _REPORT.record_read(wall_s, qlen, band_cols, backend, fallback, amortized)


def record_fault(kind: str, backend: Optional[str] = None,
                 set_index: Optional[int] = None, detail: str = "",
                 action: str = "") -> None:
    _REPORT.record_fault(kind, backend, set_index, detail, action)


def finalize_report() -> dict:
    """Render the global report to its versioned dict."""
    return _REPORT.as_dict()


def write_report(path: str, rep: Optional[dict] = None, fp=None) -> None:
    """`--report FILE` sink ('-' = stdout, or `fp` when the caller needs
    to keep stdout clean for sequence output)."""
    if rep is None:
        rep = finalize_report()
    text = json.dumps(rep, indent=1, sort_keys=False)
    if path == "-":
        (fp or sys.stdout).write(text + "\n")
    else:
        with open(path, "w") as out:
            out.write(text + "\n")


def summary(rep: dict) -> dict:
    """The compact embedding used by bench.py / microbench / chip_watcher:
    per-phase walls plus the throughput-normalization numbers, small enough
    to live inside a BENCH_* `extra` blob."""
    mfu = rep.get("mfu") or {}
    reads = rep.get("reads") or None
    return {
        "schema_version": rep["schema_version"],
        "phases": {k: v["wall_s"] for k, v in rep["phases"].items()},
        "dp_cells": rep["counters"].get("dp.cells", 0),
        "cell_updates_per_sec": mfu.get("cell_updates_per_sec"),
        "mfu": mfu.get("mfu"),
        # per-read tail latency (the item-1 service's SLO numbers)
        "read_wall_ms": ({q: reads["wall_ms"][q]
                          for q in ("p50", "p95", "p99")}
                         if reads else None),
    }


def render_report(rep: dict) -> str:
    """One-screen human rendering of a run report: phase table (sorted by
    wall, with share of total), throughput line, per-read percentiles,
    compile log totals, and the counter table. The reader for the JSON
    the `--report` flag emits — `abpoa-tpu report FILE` and
    tools/report_view.py both route here."""
    lines = []
    total = rep.get("total_wall_s") or 0.0
    ver = rep.get("schema_version")
    lines.append(f"run report (schema v{ver})  total {total:.3f}s")
    dev = rep.get("device")
    if dev:
        lines.append(f"device: {dev.get('platform', '?')} "
                     f"{dev.get('kind', '')} x{dev.get('count', 1)}".rstrip())

    phases = rep.get("phases") or {}
    if phases:
        lines.append("")
        lines.append(f"  {'phase':<16} {'wall_s':>9} {'share':>6} {'calls':>7}")
        covered = 0.0
        for name, ph in sorted(phases.items(),
                               key=lambda kv: -kv[1]["wall_s"]):
            w = ph["wall_s"]
            covered += w
            share = (100.0 * w / total) if total else 0.0
            lines.append(f"  {name:<16} {w:>9.4f} {share:>5.1f}% "
                         f"{ph['calls']:>7}")
        if total:
            lines.append(f"  {'(covered)':<16} {covered:>9.4f} "
                         f"{100.0 * covered / total:>5.1f}%")

    mfu = rep.get("mfu") or {}
    if mfu:
        cups = mfu.get("cell_updates_per_sec")
        bits = [f"dp cells {rep['counters'].get('dp.cells', 0):,}"]
        if cups:
            bits.append(f"{cups:,.0f} cell-updates/s")
        if mfu.get("mfu") is not None:
            bits.append(f"MFU {100.0 * mfu['mfu']:.3f}%")
        lines.append("")
        lines.append("throughput: " + "  ".join(bits))

    reads = rep.get("reads")
    if reads:
        wm = reads["wall_ms"]
        lines.append("")
        lines.append(f"reads: {reads['count']:,}"
                     + (f" (+{reads['dropped']:,} dropped)"
                        if reads.get("dropped") else "")
                     + (f", {reads['amortized']:,} amortized"
                        if reads.get("amortized") else ""))
        lines.append(f"  wall ms  p50 {wm['p50']}  p95 {wm['p95']}  "
                     f"p99 {wm['p99']}  max {wm['max']}")
        if reads.get("backends"):
            lines.append("  backends: " + "  ".join(
                f"{k}={v}" for k, v in reads["backends"].items()))
        if reads.get("fallbacks"):
            lines.append("  fallbacks: " + "  ".join(
                f"{k}={v}" for k, v in reads["fallbacks"].items()))

    comp = rep.get("compiles")
    if comp:
        lines.append("")
        lines.append(f"compiles: {comp['misses']} compiled / "
                     f"{comp['hits']} cache hits"
                     + (f", {comp['xla_compile_s']:.3f}s in XLA"
                        if comp.get("xla_compile_s") else ""))

    # v3: fault history + active demotions — the operator's view of what
    # the degradation ladder absorbed (resilience/), without raw JSON
    faults = rep.get("faults")
    if faults:
        lines.append("")
        lines.append(f"faults: {faults['count']:,}"
                     + (f" (+{faults['dropped']:,} dropped)"
                        if faults.get("dropped") else "")
                     + "  " + "  ".join(f"{k}={v}" for k, v in
                                        faults["kinds"].items()))
        for rec in faults["records"][:20]:
            where = (f" set {rec['set']}" if "set" in rec
                     else (f" [{rec['backend']}]" if "backend" in rec
                           else ""))
            act = f" -> {rec['action']}" if rec.get("action") else ""
            det = f": {rec['detail']}" if rec.get("detail") else ""
            lines.append(f"  t+{rec['t_s']:.2f}s {rec['kind']}{where}"
                         f"{act}{det}")
        if len(faults["records"]) > 20:
            lines.append(f"  ... {len(faults['records']) - 20} more "
                         "(see the JSON report)")
    degraded = rep.get("degraded")
    if degraded:
        lines.append("")
        lines.append("degraded (circuit breakers open at end of run):")
        for backend, d in degraded.items():
            lines.append(f"  {backend} -> {d['to']}  after {d['failures']} "
                         f"failures (last: {d['reason']})")
    quarantined = ((rep.get("counters") or {}).get("quarantine.sets")
                   or (faults or {}).get("kinds", {}).get("poisoned_set"))
    if quarantined:
        lines.append("")
        lines.append(f"quarantined sets: {quarantined} "
                     "(see faults records with a set index)")

    counters = rep.get("counters") or {}
    if counters:
        lines.append("")
        lines.append("counters:")
        for name, v in sorted(counters.items()):
            lines.append(f"  {name:<28} {v:,}")
    return "\n".join(lines) + "\n"
