"""Structured run telemetry: the process-global `RunReport`.

The stderr verbosity ladder (utils/logging.py) answers "what happened";
this module answers "where did the time go" in machine-readable form —
the per-phase / per-counter attribution accelerated-alignment papers
report (SeGraM's per-stage cycle breakdowns, arXiv:2205.05883; AnySeq/GPU's
cell-updates-per-second per kernel stage, arXiv:2205.07610). One global
report per run, reset by `start_run()`, rendered by `finalize_report()`
into a versioned JSON schema (SCHEMA/SCHEMA_VERSION below).

Overhead contract: every hook is host-side aggregation of values the
pipeline already holds (dict increments, two `perf_counter()` calls per
phase enter/exit). Nothing here adds device syncs to the hot loop;
tests/test_obs.py guards warm-run wall with reporting on vs off.
"""
from __future__ import annotations

import contextlib
import json
import sys
import time
from typing import Dict, Iterator, Optional

SCHEMA = "abpoa-tpu-run-report"
SCHEMA_VERSION = 1

# top-level keys of the rendered report, in schema order. Goldened by
# tests/test_obs.py: adding a key is a SCHEMA_VERSION bump.
SCHEMA_KEYS = ("schema", "schema_version", "created", "total_wall_s",
               "phase_wall_sum_s", "phases", "counters", "values",
               "device", "mfu")


class RunReport:
    """Phase timers + counters + value summaries for one run."""

    __slots__ = ("enabled", "t_start", "phases", "counters", "values")

    def __init__(self) -> None:
        self.enabled = True
        self.reset()

    def reset(self) -> None:
        self.t_start = time.perf_counter()
        self.phases: Dict[str, list] = {}    # name -> [wall_s, calls]
        self.counters: Dict[str, int] = {}   # name -> int
        self.values: Dict[str, list] = {}    # name -> [count, sum, min, max]

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Accumulating wall-clock timer; re-entries add up. Phases are
        non-overlapping by convention (pipeline.py) so their sum is a
        partition of run wall time."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            rec = self.phases.get(name)
            if rec is None:
                self.phases[name] = [dt, 1]
            else:
                rec[0] += dt
                rec[1] += 1

    def count(self, name: str, n: int = 1) -> None:
        if self.enabled:
            self.counters[name] = self.counters.get(name, 0) + n

    def observe(self, name: str, value: float) -> None:
        """Value summary (count/sum/min/max) — a histogram's moments without
        bucket bookkeeping in the hot path."""
        if not self.enabled:
            return
        rec = self.values.get(name)
        if rec is None:
            self.values[name] = [1, value, value, value]
        else:
            rec[0] += 1
            rec[1] += value
            if value < rec[2]:
                rec[2] = value
            if value > rec[3]:
                rec[3] = value

    def record_dp(self, rows: int, band_cols: int, gap_mode: int) -> None:
        """Account one DP dispatch: band extent and cell totals, so reads/s
        can be normalized to cell-updates/s (the AnySeq/GPU metric). Values
        come from host-side planning state (graph row count, band formula)
        — never from a device readback."""
        self.record_dp_cells(rows * band_cols, 1, band_cols, gap_mode)

    def record_dp_cells(self, cells: int, dispatches: int, band_cols: int,
                        gap_mode: int) -> None:
        """Pre-aggregated DP accounting (the fused loop reports its whole
        run at once from a host-side model). Single owner of the dp.*
        counter schema."""
        if not self.enabled:
            return
        from .mfu import CELL_INT_OPS
        self.observe("dp.band_width", band_cols)
        self.count("dp.dispatches", dispatches)
        self.count("dp.cells", cells)
        self.count("dp.cell_ops", cells * CELL_INT_OPS.get(gap_mode, 16))

    # ----------------------------------------------------------- rendering
    def as_dict(self) -> dict:
        from .mfu import mfu_block
        total = time.perf_counter() - self.t_start
        phases = {k: {"wall_s": round(v[0], 6), "calls": v[1]}
                  for k, v in sorted(self.phases.items())}
        values = {k: {"count": v[0], "sum": v[1], "min": v[2], "max": v[3]}
                  for k, v in sorted(self.values.items())}
        dev = _device_info()
        rep = {
            "schema": SCHEMA,
            "schema_version": SCHEMA_VERSION,
            "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "total_wall_s": round(total, 6),
            "phase_wall_sum_s": round(sum(v[0] for v in self.phases.values()),
                                      6),
            "phases": phases,
            "counters": dict(sorted(self.counters.items())),
            "values": values,
            "device": dev,
            "mfu": mfu_block(self, dev),
        }
        return rep


def _device_info() -> Optional[dict]:
    """Accelerator identity, host-side only: queried exclusively when jax is
    already imported (a device path ran), so a native/numpy run never pays a
    jax import — and never risks a wedged-tunnel hang — for its report."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        d = jax.devices()[0]
        return {"backend": "jax", "platform": str(d.platform),
                "kind": str(getattr(d, "device_kind", "") or "")}
    except Exception:
        return None


# --------------------------------------------------------------------------- #
# process-global registry                                                     #
# --------------------------------------------------------------------------- #

_REPORT = RunReport()


def report() -> RunReport:
    return _REPORT


def start_run() -> None:
    """Reset the global report; call at the top of each CLI/pyapi run."""
    _REPORT.reset()


def set_enabled(flag: bool) -> None:
    """Telemetry kill switch (the overhead-guard test's control arm)."""
    _REPORT.enabled = bool(flag)


def phase(name: str):
    return _REPORT.phase(name)


def count(name: str, n: int = 1) -> None:
    _REPORT.count(name, n)


def observe(name: str, value: float) -> None:
    _REPORT.observe(name, value)


def record_dp(rows: int, band_cols: int, gap_mode: int) -> None:
    _REPORT.record_dp(rows, band_cols, gap_mode)


def finalize_report() -> dict:
    """Render the global report to its versioned dict."""
    return _REPORT.as_dict()


def write_report(path: str, rep: Optional[dict] = None, fp=None) -> None:
    """`--report FILE` sink ('-' = stdout, or `fp` when the caller needs
    to keep stdout clean for sequence output)."""
    if rep is None:
        rep = finalize_report()
    text = json.dumps(rep, indent=1, sort_keys=False)
    if path == "-":
        (fp or sys.stdout).write(text + "\n")
    else:
        with open(path, "w") as out:
            out.write(text + "\n")


def summary(rep: dict) -> dict:
    """The compact embedding used by bench.py / microbench / chip_watcher:
    per-phase walls plus the throughput-normalization numbers, small enough
    to live inside a BENCH_* `extra` blob."""
    mfu = rep.get("mfu") or {}
    return {
        "schema_version": rep["schema_version"],
        "phases": {k: v["wall_s"] for k, v in rep["phases"].items()},
        "dp_cells": rep["counters"].get("dp.cells", 0),
        "cell_updates_per_sec": mfu.get("cell_updates_per_sec"),
        "mfu": mfu.get("mfu"),
    }
