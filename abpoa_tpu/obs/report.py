"""Structured run telemetry: the process-global `RunReport`.

The stderr verbosity ladder (utils/logging.py) answers "what happened";
this module answers "where did the time go" in machine-readable form —
the per-phase / per-counter attribution accelerated-alignment papers
report (SeGraM's per-stage cycle breakdowns, arXiv:2205.05883; AnySeq/GPU's
cell-updates-per-second per kernel stage, arXiv:2205.07610). One global
report per run, reset by `start_run()`, rendered by `finalize_report()`
into a versioned JSON schema (SCHEMA/SCHEMA_VERSION below).

Overhead contract: every hook is host-side aggregation of values the
pipeline already holds (dict increments, two `perf_counter()` calls per
phase enter/exit). Nothing here adds device syncs to the hot loop;
tests/test_obs.py guards warm-run wall with reporting on vs off.
"""
from __future__ import annotations

import contextlib
import json
import math
import os
import sys
import time
from typing import Dict, Iterator, Optional

from . import compile_log as _clog
from . import metrics as _metrics
from . import trace as _trace

SCHEMA = "abpoa-tpu-run-report"
SCHEMA_VERSION = 4

# top-level keys of the rendered report, in schema order. Goldened by
# tests/test_obs.py: adding a key is a SCHEMA_VERSION bump.
# v2 adds `reads` (per-read latency records -> p50/p95/p99, the item-1
# service's SLO numbers) and `compiles` (the compile log, compile_log.py).
# v3 adds `faults` (every absorbed dispatch failure / quarantined set,
# abpoa_tpu/resilience) and `degraded` (circuit-breaker demotions active
# at the end of the run) — a clean run carries null for both.
# v4 re-bases the `reads` block on the streaming log-bucket sketch
# (obs/metrics.py LogSketch): `count`/`wall_ms`/`backends`/`fallbacks`
# now cover EVERY read — honest p50/p95/p99 past READS_CAP in O(1)
# memory — while raw records (bounded by READS_CAP, `records_kept`) feed
# only the qlen/band attribution tables.
SCHEMA_KEYS = ("schema", "schema_version", "created", "total_wall_s",
               "phase_wall_sum_s", "phases", "counters", "values",
               "reads", "compiles", "faults", "degraded", "device", "mfu")

# raw per-read record bound. Since v4 this caps only the attribution
# tables (qlen/band extents): the wall percentiles come from the sketch,
# which sees every read, so they stay honest for a long-lived process
# streaming millions of reads. Records past the cap are still counted
# (`reads.dropped`).
READS_CAP = 100_000

# fault-record bound (same contract as READS_CAP): a fault storm must not
# grow the report without bound, but the drops are counted
FAULTS_CAP = 256


class RunReport:
    """Phase timers + counters + value summaries for one run."""

    __slots__ = ("enabled", "t_start", "phases", "counters", "values",
                 "reads", "reads_dropped", "wall_sketch", "read_backends",
                 "read_fallbacks", "reads_amortized", "faults",
                 "faults_dropped", "degraded")

    def __init__(self) -> None:
        self.enabled = True
        self.reset()

    def reset(self) -> None:
        self.t_start = time.perf_counter()
        self.phases: Dict[str, list] = {}    # name -> [wall_s, calls]
        self.counters: Dict[str, int] = {}   # name -> int
        self.values: Dict[str, list] = {}    # name -> [count, sum, min, max]
        # (wall_s, qlen, band_cols, backend, fallback, amortized)
        self.reads: list = []
        self.reads_dropped = 0
        # v4: the percentile path — a bounded mergeable sketch over EVERY
        # read's wall, plus exact O(1) attribution dicts; the raw list
        # above only feeds the qlen/band tables
        self.wall_sketch = _metrics.LogSketch()
        self.read_backends: Dict[str, int] = {}
        self.read_fallbacks: Dict[str, int] = {}
        self.reads_amortized = 0
        # absorbed failures (resilience layer): dicts, FAULTS_CAP-bounded
        self.faults: list = []
        self.faults_dropped = 0
        # backend -> {"to", "reason", "failures"} (circuit-breaker opens)
        self.degraded: Dict[str, dict] = {}
        _clog.reset_run()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Accumulating wall-clock timer; re-entries add up. Phases are
        non-overlapping by convention (pipeline.py) so their sum is a
        partition of run wall time. The same (t0, dt) measurement feeds
        the trace timeline, so phase spans reconcile with phase timers
        exactly."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            # metrics._MUT (RLock) also guards this report's accumulators:
            # serve workers are concurrent publishers of BOTH surfaces,
            # and an unlocked read-modify-write here would make the final
            # report disagree with the (locked) Prometheus counters
            with _metrics._MUT:
                rec = self.phases.get(name)
                if rec is None:
                    self.phases[name] = [dt, 1]
                else:
                    rec[0] += dt
                    rec[1] += 1
            _trace.add_span(name, "phase", t0, dt)
            _metrics.publish_phase(name, dt)

    def merge_phase(self, name: str, wall_s: float, calls: int = 1) -> None:
        """Fold a phase-wall delta measured in ANOTHER process (a pool
        worker's job extract) into this report + the fleet registry —
        same accumulation the phase() context performs, without a timer
        (the wall was measured where the work ran)."""
        if not self.enabled:
            return
        with _metrics._MUT:
            rec = self.phases.get(name)
            if rec is None:
                self.phases[name] = [wall_s, calls]
            else:
                rec[0] += wall_s
                rec[1] += calls
        _metrics.publish_phase(name, wall_s)

    def merge_value(self, name: str, count: int, total: float,
                    vmin: float, vmax: float) -> None:
        """Fold an observe() summary delta from another process."""
        if not self.enabled:
            return
        with _metrics._MUT:
            rec = self.values.get(name)
            if rec is None:
                self.values[name] = [count, total, vmin, vmax]
            else:
                rec[0] += count
                rec[1] += total
                if vmin < rec[2]:
                    rec[2] = vmin
                if vmax > rec[3]:
                    rec[3] = vmax

    def count(self, name: str, n: int = 1) -> None:
        if self.enabled:
            with _metrics._MUT:
                self.counters[name] = self.counters.get(name, 0) + n
            # mirror into the process-cumulative fleet registry (curated
            # Prometheus families; names outside the map stay run-local)
            _metrics.publish_counter(name, n)

    def observe(self, name: str, value: float) -> None:
        """Value summary (count/sum/min/max) — a histogram's moments without
        bucket bookkeeping in the hot path."""
        if not self.enabled:
            return
        with _metrics._MUT:
            rec = self.values.get(name)
            if rec is None:
                self.values[name] = [1, value, value, value]
            else:
                rec[0] += 1
                rec[1] += value
                if value < rec[2]:
                    rec[2] = value
                if value > rec[3]:
                    rec[3] = value

    def record_dp(self, rows: int, band_cols: int, gap_mode: int) -> None:
        """Account one DP dispatch: band extent and cell totals, so reads/s
        can be normalized to cell-updates/s (the AnySeq/GPU metric). Values
        come from host-side planning state (graph row count, band formula)
        — never from a device readback."""
        self.record_dp_cells(rows * band_cols, 1, band_cols, gap_mode)

    def record_dp_cells(self, cells: int, dispatches: int, band_cols: int,
                        gap_mode: int) -> None:
        """Pre-aggregated DP accounting (the fused loop reports its whole
        run at once from a host-side model). Single owner of the dp.*
        counter schema."""
        if not self.enabled:
            return
        from .mfu import CELL_INT_OPS
        self.observe("dp.band_width", band_cols)
        self.count("dp.dispatches", dispatches)
        self.count("dp.cells", cells)
        self.count("dp.cell_ops", cells * CELL_INT_OPS.get(gap_mode, 16))

    def record_read(self, wall_s: float, qlen: int, band_cols: int,
                    backend: str, fallback: Optional[str] = None,
                    amortized: bool = False) -> None:
        """One per-read latency record (the SLO stream): wall seconds, read
        length, planned band extent, the backend that ran it, and the
        fallback reason when a faster path was bypassed. `amortized` marks
        records derived from a multi-read dispatch (fused loop / lockstep
        batch) whose wall was split evenly across its reads — the per-read
        number is then a share, not an independent measurement."""
        if not self.enabled:
            return
        # the sketch and the attribution dicts see EVERY read (O(1) each);
        # only the raw record list is capped. One lock spans the whole
        # record so concurrent serve workers keep count/sketch consistent
        with _metrics._MUT:
            self.wall_sketch.observe(wall_s)
            self.read_backends[backend] = \
                self.read_backends.get(backend, 0) + 1
            if fallback:
                self.read_fallbacks[fallback] = \
                    self.read_fallbacks.get(fallback, 0) + 1
            if amortized:
                self.reads_amortized += 1
            if len(self.reads) < READS_CAP:
                self.reads.append((wall_s, qlen, band_cols, backend,
                                   fallback, amortized))
            else:
                self.reads_dropped += 1
        _metrics.publish_read(wall_s, backend, fallback)

    def record_fault(self, kind: str, backend: Optional[str] = None,
                     set_index: Optional[int] = None, detail: str = "",
                     action: str = "", extra: Optional[dict] = None) -> None:
        """One absorbed failure (abpoa_tpu/resilience): what failed, where
        it was headed, and what the degradation ladder did about it. The
        contract of that layer is that NOTHING is swallowed silently —
        every fallback/demotion/quarantine lands here (and in the
        `faults.<kind>` counter) even when the run then succeeds. `extra`
        carries flat cross-reference fields (request_id, attempt, the
        harvested flight-dump path) that tie the fault to its request."""
        if not self.enabled:
            return
        self.count(f"faults.{kind}")
        rec = {"kind": kind, "t_s": round(time.perf_counter() - self.t_start,
                                          4)}
        if backend:
            rec["backend"] = backend
        if set_index is not None:
            rec["set"] = set_index
        if detail:
            rec["detail"] = detail
        if action:
            rec["action"] = action
        if extra:
            for k, v in extra.items():
                if v is not None and k not in rec:
                    rec[k] = v
        with _metrics._MUT:
            if len(self.faults) >= FAULTS_CAP:
                self.faults_dropped += 1
            else:
                self.faults.append(rec)

    def mark_degraded(self, backend: str, to: str, reason: str,
                      failures: int) -> None:
        """A circuit-breaker open: `backend` serves as `to` until the
        breaker recloses (resilience/breaker.py is the single caller)."""
        if self.enabled:
            self.degraded[backend] = {"to": to, "reason": reason,
                                      "failures": failures}

    def mark_reclosed(self, backend: str) -> None:
        """A half-open probe succeeded: the backend left the `degraded`
        block (which reports breakers open NOW, not historically — the
        open/reclose history lives in the breaker.* counters)."""
        if self.enabled:
            self.degraded.pop(backend, None)

    # ----------------------------------------------------------- rendering
    def _faults_block(self) -> Optional[dict]:
        if not self.faults and not self.faults_dropped:
            return None
        kinds: Dict[str, int] = {}
        for rec in self.faults:
            kinds[rec["kind"]] = kinds.get(rec["kind"], 0) + 1
        return {
            "count": len(self.faults) + self.faults_dropped,
            "dropped": self.faults_dropped,
            "kinds": dict(sorted(kinds.items())),
            "records": self.faults,
        }

    def _reads_block(self) -> Optional[dict]:
        """Tail-latency aggregation of the per-read stream (schema v4):
        `count`, `wall_ms` percentiles, `backends`/`fallbacks` cover every
        read via the streaming sketch + O(1) dicts; the qlen/band
        attribution tables come from the raw records (READS_CAP-bounded,
        `records_kept`/`dropped`)."""
        sk = self.wall_sketch
        if sk.count == 0:
            return None
        n = sk.count
        qlens = [r[1] for r in self.reads]
        bands = [r[2] for r in self.reads]
        nk = len(self.reads)

        def ms(x):
            return round(x * 1e3, 4) if x is not None else None

        return {
            "count": n,
            "records_kept": nk,
            "dropped": self.reads_dropped,
            "amortized": self.reads_amortized,
            "backends": dict(sorted(self.read_backends.items())),
            "fallbacks": dict(sorted(self.read_fallbacks.items())),
            "wall_ms": {
                "p50": ms(sk.quantile(0.50)),
                "p95": ms(sk.quantile(0.95)),
                "p99": ms(sk.quantile(0.99)),
                "mean": ms(sk.sum / n),
                "max": ms(sk.max),
            },
            # sketch provenance: a reader can tell these percentiles carry
            # a declared tolerance instead of nearest-rank exactness
            "sketch": {"kind": "log-bucket",
                       "relative_error": sk.RELATIVE_ERROR},
            "qlen": {"min": min(qlens), "max": max(qlens),
                     "mean": round(sum(qlens) / nk, 1)} if nk else None,
            "band_cols": {"min": min(bands),
                          "max": max(bands)} if nk else None,
        }

    @staticmethod
    def _compiles_block() -> Optional[dict]:
        """The run's compile log (compile_log.py): per-dispatch records for
        the jitted entry points, with XLA compile seconds and persistent-
        cache verdicts when the monitoring events fired."""
        recs = _clog.run_records()
        dropped = _clog.run_dropped()
        if not recs and not dropped:
            return None
        misses = sum(1 for r in recs if not r["cache_hit"])
        xla = sum(r.get("xla_compile_s") or 0.0 for r in recs)
        return {
            "count": len(recs) + dropped,
            "dropped": dropped,
            "misses": misses,
            "hits": len(recs) - misses,
            "xla_compile_s": round(xla, 6),
            "records": recs,
        }

    def as_dict(self) -> dict:
        from .mfu import mfu_block
        total = time.perf_counter() - self.t_start
        phases = {k: {"wall_s": round(v[0], 6), "calls": v[1]}
                  for k, v in sorted(self.phases.items())}
        values = {k: {"count": v[0], "sum": v[1], "min": v[2], "max": v[3]}
                  for k, v in sorted(self.values.items())}
        dev = _device_info()
        rep = {
            "schema": SCHEMA,
            "schema_version": SCHEMA_VERSION,
            "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "total_wall_s": round(total, 6),
            "phase_wall_sum_s": round(sum(v[0] for v in self.phases.values()),
                                      6),
            "phases": phases,
            "counters": dict(sorted(self.counters.items())),
            "values": values,
            "reads": self._reads_block(),
            "compiles": self._compiles_block(),
            "faults": self._faults_block(),
            "degraded": dict(sorted(self.degraded.items())) or None,
            "device": dev,
            "mfu": mfu_block(self, dev),
        }
        return rep


def exact_percentile(sorted_vals, q: float):
    """Nearest-rank percentile over an ascending list (no interpolation).
    The sketch-tolerance tests use this as the exact reference the
    LogSketch estimates are judged against."""
    if not sorted_vals:
        return None
    i = max(0, min(len(sorted_vals) - 1,
                   math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[i]


def _device_info() -> Optional[dict]:
    """Accelerator identity, host-side only: queried exclusively when jax is
    already imported (a device path ran), so a native/numpy run never pays a
    jax import — and never risks a wedged-tunnel hang — for its report."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        d = jax.devices()[0]
        return {"backend": "jax", "platform": str(d.platform),
                "kind": str(getattr(d, "device_kind", "") or "")}
    except Exception:
        return None


# --------------------------------------------------------------------------- #
# process-global registry                                                     #
# --------------------------------------------------------------------------- #

_REPORT = RunReport()


def _metrics_collector(reg) -> None:
    """Render-time gauges too cheap to bother pushing per event: the trace
    ring's drop count and the device identity + MFU peak (readable only
    once a device path made jax live — _device_info never imports jax)."""
    reg.gauge("abpoa_trace_dropped_events",
              "Trace ring-buffer events overwritten before export").set(
        _trace.tracer().dropped)
    dev = _device_info()
    if dev:
        reg.gauge("abpoa_device_info",
                  "Accelerator identity (value is always 1)").set(
            1, platform=dev.get("platform", ""), kind=dev.get("kind", ""))
        from .mfu import peak_ops_for_kind
        peak = peak_ops_for_kind(dev.get("kind") or "")
        if peak:
            reg.gauge("abpoa_device_peak_ops_per_second",
                      "Peak int-op throughput of the attached device "
                      "(MFU denominator)").set(peak)


_metrics.register_global_collector(_metrics_collector)


def report() -> RunReport:
    return _REPORT


def start_run() -> None:
    """Reset the global report; call at the top of each CLI/pyapi run."""
    _REPORT.reset()
    _metrics.publish_run_start()
    # run-scoped gauges must not outlive their run in the exposition
    _metrics.clear_batch_progress()
    # backend-resolution state is process-global too; a new run must not
    # inherit the previous run's resolved kernel as a telemetry label
    try:
        from ..align.dispatch import _LAST_RESOLVED
        _LAST_RESOLVED["name"] = ""
        _LAST_RESOLVED["reason"] = None
    except Exception:
        pass
    # circuit-breaker demotions are run-scoped ("for the remainder of the
    # run"): a fresh run gets the requested backend back
    try:
        from ..resilience.breaker import breaker
        breaker().reset()
    except Exception:
        pass


def set_enabled(flag: bool) -> None:
    """Telemetry kill switch (the overhead-guard test's control arm)."""
    _REPORT.enabled = bool(flag)


def phase(name: str):
    return _REPORT.phase(name)


def count(name: str, n: int = 1) -> None:
    _REPORT.count(name, n)


def observe(name: str, value: float) -> None:
    _REPORT.observe(name, value)


def record_dp(rows: int, band_cols: int, gap_mode: int) -> None:
    _REPORT.record_dp(rows, band_cols, gap_mode)


def record_read(wall_s: float, qlen: int, band_cols: int, backend: str,
                fallback: Optional[str] = None,
                amortized: bool = False) -> None:
    _REPORT.record_read(wall_s, qlen, band_cols, backend, fallback, amortized)


def record_fault(kind: str, backend: Optional[str] = None,
                 set_index: Optional[int] = None, detail: str = "",
                 action: str = "", extra: Optional[dict] = None) -> None:
    _REPORT.record_fault(kind, backend, set_index, detail, action, extra)


def finalize_report() -> dict:
    """Render the global report to its versioned dict."""
    return _REPORT.as_dict()


def write_report(path: str, rep: Optional[dict] = None, fp=None) -> None:
    """`--report FILE` sink ('-' = stdout, or `fp` when the caller needs
    to keep stdout clean for sequence output)."""
    if rep is None:
        rep = finalize_report()
    text = json.dumps(rep, indent=1, sort_keys=False)
    if path == "-":
        (fp or sys.stdout).write(text + "\n")
    else:
        with open(path, "w") as out:
            out.write(text + "\n")


def summary(rep: dict) -> dict:
    """The compact embedding used by bench.py / microbench / chip_watcher:
    per-phase walls plus the throughput-normalization numbers, small enough
    to live inside a BENCH_* `extra` blob."""
    mfu = rep.get("mfu") or {}
    reads = rep.get("reads") or None
    return {
        "schema_version": rep["schema_version"],
        "phases": {k: v["wall_s"] for k, v in rep["phases"].items()},
        "dp_cells": rep["counters"].get("dp.cells", 0),
        "cell_updates_per_sec": mfu.get("cell_updates_per_sec"),
        "mfu": mfu.get("mfu"),
        # per-read tail latency (the item-1 service's SLO numbers)
        "read_wall_ms": ({q: reads["wall_ms"][q]
                          for q in ("p50", "p95", "p99")}
                         if reads else None),
    }


def render_report(rep: dict) -> str:
    """One-screen human rendering of a run report: phase table (sorted by
    wall, with share of total), throughput line, per-read percentiles,
    compile log totals, and the counter table. The reader for the JSON
    the `--report` flag emits — `abpoa-tpu report FILE` and
    tools/report_view.py both route here."""
    lines = []
    total = rep.get("total_wall_s") or 0.0
    ver = rep.get("schema_version")
    lines.append(f"run report (schema v{ver})  total {total:.3f}s")
    dev = rep.get("device")
    if dev:
        lines.append(f"device: {dev.get('platform', '?')} "
                     f"{dev.get('kind', '')} x{dev.get('count', 1)}".rstrip())

    phases = rep.get("phases") or {}
    if phases:
        lines.append("")
        lines.append(f"  {'phase':<16} {'wall_s':>9} {'share':>6} {'calls':>7}")
        covered = 0.0
        for name, ph in sorted(phases.items(),
                               key=lambda kv: -kv[1]["wall_s"]):
            w = ph["wall_s"]
            covered += w
            share = (100.0 * w / total) if total else 0.0
            lines.append(f"  {name:<16} {w:>9.4f} {share:>5.1f}% "
                         f"{ph['calls']:>7}")
        if total:
            lines.append(f"  {'(covered)':<16} {covered:>9.4f} "
                         f"{100.0 * covered / total:>5.1f}%")

    mfu = rep.get("mfu") or {}
    if mfu:
        cups = mfu.get("cell_updates_per_sec")
        bits = [f"dp cells {rep['counters'].get('dp.cells', 0):,}"]
        if cups:
            bits.append(f"{cups:,.0f} cell-updates/s")
        if mfu.get("mfu") is not None:
            bits.append(f"MFU {100.0 * mfu['mfu']:.3f}%")
        lines.append("")
        lines.append("throughput: " + "  ".join(bits))

    reads = rep.get("reads")
    if reads:
        wm = reads["wall_ms"]
        lines.append("")
        lines.append(f"reads: {reads['count']:,}"
                     + (f" (qlen/band tables over the first "
                        f"{reads.get('records_kept', 0):,} records)"
                        if reads.get("dropped") else "")
                     + (f", {reads['amortized']:,} amortized"
                        if reads.get("amortized") else ""))
        sk = reads.get("sketch")
        tol = (f" (sketch, ±{100 * sk['relative_error']:.0f}%)"
               if sk else "")
        lines.append(f"  wall ms  p50 {wm['p50']}  p95 {wm['p95']}  "
                     f"p99 {wm['p99']}  max {wm['max']}{tol}")
        if reads.get("backends"):
            lines.append("  backends: " + "  ".join(
                f"{k}={v}" for k, v in reads["backends"].items()))
        if reads.get("fallbacks"):
            lines.append("  fallbacks: " + "  ".join(
                f"{k}={v}" for k, v in reads["fallbacks"].items()))

    comp = rep.get("compiles")
    if comp:
        lines.append("")
        lines.append(f"compiles: {comp['misses']} compiled / "
                     f"{comp['hits']} cache hits"
                     + (f", {comp['xla_compile_s']:.3f}s in XLA"
                        if comp.get("xla_compile_s") else ""))

    # v3: fault history + active demotions — the operator's view of what
    # the degradation ladder absorbed (resilience/), without raw JSON
    faults = rep.get("faults")
    if faults:
        lines.append("")
        lines.append(f"faults: {faults['count']:,}"
                     + (f" (+{faults['dropped']:,} dropped)"
                        if faults.get("dropped") else "")
                     + "  " + "  ".join(f"{k}={v}" for k, v in
                                        faults["kinds"].items()))
        for rec in faults["records"][:20]:
            where = (f" set {rec['set']}" if "set" in rec
                     else (f" [{rec['backend']}]" if "backend" in rec
                           else ""))
            act = f" -> {rec['action']}" if rec.get("action") else ""
            det = f": {rec['detail']}" if rec.get("detail") else ""
            lines.append(f"  t+{rec['t_s']:.2f}s {rec['kind']}{where}"
                         f"{act}{det}")
        if len(faults["records"]) > 20:
            lines.append(f"  ... {len(faults['records']) - 20} more "
                         "(see the JSON report)")
    degraded = rep.get("degraded")
    if degraded:
        lines.append("")
        lines.append("degraded (circuit breakers open at end of run):")
        for backend, d in degraded.items():
            lines.append(f"  {backend} -> {d['to']}  after {d['failures']} "
                         f"failures (last: {d['reason']})")
    quarantined = ((rep.get("counters") or {}).get("quarantine.sets")
                   or (faults or {}).get("kinds", {}).get("poisoned_set"))
    if quarantined:
        lines.append("")
        lines.append(f"quarantined sets: {quarantined} "
                     "(see faults records with a set index)")

    counters = rep.get("counters") or {}
    if counters:
        lines.append("")
        lines.append("counters:")
        for name, v in sorted(counters.items()):
            lines.append(f"  {name:<28} {v:,}")
    return "\n".join(lines) + "\n"


def _diff_rows(rep: dict) -> dict:
    """The comparable scalar slice of a run report (either schema
    direction: v2+ reports all carry these or render as n/a)."""
    reads = rep.get("reads") or {}
    wall_ms = reads.get("wall_ms") or {}
    comp = rep.get("compiles") or {}
    mfu = rep.get("mfu") or {}
    counters = rep.get("counters") or {}
    total = rep.get("total_wall_s") or 0.0
    n_reads = reads.get("count") or 0
    rows = {"total_wall_s": total,
            "reads": n_reads,
            "reads_per_sec": (n_reads / total) if total and n_reads
            else None,
            "read_p50_ms": wall_ms.get("p50"),
            "read_p99_ms": wall_ms.get("p99"),
            "cell_updates_per_sec": mfu.get("cell_updates_per_sec"),
            "dp_cells": counters.get("dp.cells"),
            "compile_misses": comp.get("misses"),
            "compile_hits": comp.get("hits"),
            "faults": (rep.get("faults") or {}).get("count", 0),
            "quarantined_sets": counters.get("quarantine.sets", 0)}
    for name, ph in sorted((rep.get("phases") or {}).items()):
        rows[f"phase.{name}_s"] = ph.get("wall_s")
    return rows


# fields where bigger is better (delta coloring of the diff): everything
# else is a cost
_DIFF_HIGHER_BETTER = {"reads_per_sec", "cell_updates_per_sec",
                       "compile_hits"}


def render_report_diff(rep_a: dict, rep_b: dict,
                       label_a: str = "A", label_b: str = "B") -> str:
    """`abpoa-tpu report --diff A B`: side-by-side per-field comparison
    of two run reports (phase walls, reads/s, CUPS, compiles, faults)
    with absolute delta and percent change — the manual perf-triage loop
    without eyeballing two JSON blobs."""
    rows_a, rows_b = _diff_rows(rep_a), _diff_rows(rep_b)
    names = list(rows_a)
    names.extend(k for k in rows_b if k not in rows_a)
    la = (os.path.basename(label_a) or label_a)[:16]
    lb = (os.path.basename(label_b) or label_b)[:16]
    lines = [f"report diff: A={label_a} (schema "
             f"v{rep_a.get('schema_version')})  B={label_b} (schema "
             f"v{rep_b.get('schema_version')})",
             f"  {'field':<22} {la:>14} {lb:>14} {'delta':>12} "
             f"{'change':>8}"]

    def fmt(v):
        if v is None:
            return "n/a"
        if isinstance(v, float):
            return f"{v:,.4g}" if abs(v) < 1e6 else f"{v:,.0f}"
        return f"{v:,}"

    for name in names:
        va, vb = rows_a.get(name), rows_b.get(name)
        if va is None and vb is None:
            continue
        if va is None or vb is None:
            delta = pct = mark = ""
        else:
            d = vb - va
            delta = f"{d:+,.4g}" if isinstance(d, float) else f"{d:+,}"
            pct = f"{100.0 * d / va:+.1f}%" if va else ""
            better = (d > 0) == (name in _DIFF_HIGHER_BETTER)
            mark = "" if d == 0 else ("  +" if better else "  -")
        lines.append(f"  {name:<22} {fmt(va):>14} {fmt(vb):>14} "
                     f"{delta:>12} {pct:>8}{mark}")
    return "\n".join(lines) + "\n"
