"""SLO evaluation over the run-report archive: `abpoa-tpu slo`.

Objectives are declared in JSON (``tools/slo_objectives.json`` is the
shipped default): each names a per-run metric derived from an archive
record (obs/archive.py), a ceiling, and an error budget — the fraction
of runs in the window allowed to breach the ceiling before the
objective is VIOLATED. The evaluator prints per-objective burn rate
(bad-fraction / budget; >1 means the budget is spent) and remaining
budget, and exits nonzero on any violation — the CI-able form of
"are we still meeting the service numbers ROADMAP item 1 promises".

Objective file format::

    {
      "window_runs": 200,
      "objectives": [
        {"name": "read-p99-wall", "metric": "read_p99_ms",
         "max": 500.0, "error_budget": 0.05,
         "description": "..."},
        ...
      ]
    }

Metrics an objective can reference (each derived per run; a run missing
the metric is skipped for that objective, never counted as bad):

- ``read_p99_ms``     sketch p99 of per-read wall, milliseconds
- ``read_p50_ms``     sketch p50, milliseconds
- ``fallback_rate``   fallback reads / total reads
- ``recompile_rate``  compile misses / total jit dispatches (0 when the
                      run made no jit dispatches)
- ``fault_rate``      absorbed faults / max(1, reads)
- ``quarantine_rate`` quarantined sets per run
- ``total_wall_s``    whole-run wall seconds
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import archive

DEFAULT_WINDOW = 200


def _metric(rec: dict, name: str) -> Optional[float]:
    reads = rec.get("reads") or 0
    wall_ms = rec.get("read_wall_ms") or {}
    if name == "read_p99_ms":
        return wall_ms.get("p99")
    if name == "read_p50_ms":
        return wall_ms.get("p50")
    if name == "fallback_rate":
        if not reads:
            return None
        return (rec.get("fallback_reads") or 0) / reads
    if name == "recompile_rate":
        hits = rec.get("compile_hits") or 0
        misses = rec.get("compile_misses") or 0
        return misses / (hits + misses) if hits + misses else 0.0
    if name == "fault_rate":
        return (rec.get("faults") or 0) / max(1, reads)
    if name == "quarantine_rate":
        return float(rec.get("quarantined") or 0)
    if name == "total_wall_s":
        return rec.get("total_wall_s")
    raise ValueError(f"unknown SLO metric: {name!r}")


def evaluate(objectives: dict, records: List[dict]) -> dict:
    """-> {"window", "objectives": [...], "violated"}; per objective:
    evaluated/bad counts, bad fraction, burn rate (bad_fraction /
    error_budget) and remaining budget. Violated = budget exhausted."""
    out = []
    any_violated = False
    for obj in objectives.get("objectives", []):
        name, metric = obj["name"], obj["metric"]
        ceiling = float(obj["max"])
        budget = float(obj.get("error_budget", 0.0))
        # optional workload scope (PR 18): "map" runs amortize K-lane
        # round walls into per-read shares, so they get their own
        # ceilings; an objective without `workload` judges every run,
        # records without the field count as "consensus"
        scope = obj.get("workload")
        evaluated = bad = 0
        worst: Optional[float] = None
        offenders: List[tuple] = []   # (value, request id/label) of breaches
        for rec in records:
            if scope and (rec.get("workload") or "consensus") != scope:
                continue
            v = _metric(rec, metric)
            if v is None:
                continue
            evaluated += 1
            if worst is None or v > worst:
                worst = v
            if v > ceiling:
                bad += 1
                offenders.append(
                    (v, rec.get("request_id") or rec.get("label") or "?"))
        # the requests that BURNED the budget, worst first — each id is
        # greppable into its trace/dump via `abpoa-tpu why <id>`
        offenders.sort(key=lambda t: -t[0])
        bad_frac = bad / evaluated if evaluated else 0.0
        # zero budget means "no run may breach the ceiling": one bad run
        # reads as infinite burn
        burn = (bad_frac / budget) if budget > 0 else \
            (float("inf") if bad else 0.0)
        violated = evaluated > 0 and bad_frac > budget
        any_violated = any_violated or violated
        out.append({
            "name": name, "metric": metric, "max": ceiling,
            "error_budget": budget, "evaluated": evaluated, "bad": bad,
            "bad_fraction": round(bad_frac, 6),
            "burn_rate": (round(burn, 4)
                          if burn != float("inf") else None),
            "budget_remaining": round(max(0.0, 1.0 - burn), 4)
            if burn != float("inf") else 0.0,
            "worst": worst,
            "offenders": [{"id": oid, "value": round(v, 4)}
                          for v, oid in offenders[:5]],
            "violated": violated,
        })
    return {"window": len(records), "objectives": out,
            "violated": any_violated}


def format_table(result: dict, archive_path: str = "") -> str:
    lines = [f"SLO evaluation over {result['window']} archived runs"
             + (f"  ({archive_path})" if archive_path else "")]
    hdr = (f"  {'objective':<18} {'metric':<16} {'ceiling':>10} "
           f"{'bad/n':>9} {'budget':>7} {'burn':>6} {'left':>6}  verdict")
    lines.append(hdr)
    for o in result["objectives"]:
        burn = "inf" if o["burn_rate"] is None else f"{o['burn_rate']:.2f}"
        left = f"{100 * o['budget_remaining']:.0f}%"
        verdict = "VIOLATED" if o["violated"] else "ok"
        lines.append(
            f"  {o['name']:<18} {o['metric']:<16} {o['max']:>10g} "
            f"{o['bad']:>4}/{o['evaluated']:<4} "
            f"{100 * o['error_budget']:>6.1f}% {burn:>6} {left:>6}  "
            f"{verdict}")
        if o.get("offenders"):
            # the ids that burned the budget: `abpoa-tpu why <id>` renders
            # each one's trace + flight dump
            ids = "  ".join(f"{of['id']}({of['value']:g})"
                            for of in o["offenders"][:3])
            lines.append(f"      burned by: {ids}")
    lines.append("result: " + ("VIOLATED (error budget exhausted)"
                               if result["violated"] else
                               "ok (all objectives within budget)"))
    return "\n".join(lines) + "\n"


def default_objectives_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "tools", "slo_objectives.json")


def slo_main(argv) -> int:
    """`abpoa-tpu slo` — evaluate declared objectives against the archive
    window; rc 0 ok, 1 violated, 2 nothing to evaluate / bad input."""
    ap = argparse.ArgumentParser(
        prog="abpoa-tpu slo",
        description="evaluate SLO objectives (p99 wall, fallback-rate, "
                    "recompile-rate, fault-rate ceilings with error "
                    "budgets) against the run-report archive")
    ap.add_argument("--objectives", default=None, metavar="FILE",
                    help="objectives JSON [tools/slo_objectives.json]")
    ap.add_argument("--archive-dir", default=None, metavar="DIR",
                    help="archive directory [ABPOA_TPU_ARCHIVE_DIR or "
                         "~/.cache/abpoa_tpu/reports]")
    ap.add_argument("--window", type=int, default=None, metavar="N",
                    help="newest N runs to evaluate [objectives file "
                         f"window_runs, else {DEFAULT_WINDOW}]")
    ap.add_argument("--fleet", action="store_true",
                    help="evaluate the merged window across every "
                         "replica archive (replica-* subdirs of the "
                         "archive dir, as laid out by `abpoa-tpu fleet`)")
    ap.add_argument("--json", default=None, metavar="FILE",
                    help="also write the machine-readable result "
                         "('-' for stdout)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the table (exit status only)")
    args = ap.parse_args(argv)
    if args.archive_dir:
        os.environ["ABPOA_TPU_ARCHIVE_DIR"] = args.archive_dir
    obj_path = args.objectives or default_objectives_path()
    try:
        with open(obj_path) as fp:
            objectives = json.load(fp)
    except (OSError, ValueError) as e:
        print(f"Error: cannot load objectives {obj_path}: {e}",
              file=sys.stderr)
        return 2
    window = args.window or objectives.get("window_runs", DEFAULT_WINDOW)
    if args.fleet:
        records = archive.read_fleet_window(window)
    else:
        records = archive.read_window(window)
    if not records:
        print(f"Error: no archived runs under {archive.archive_dir()} "
              "(run with archiving enabled first; see --report/--metrics "
              "docs)", file=sys.stderr)
        return 2
    try:
        result = evaluate(objectives, records)
    except (KeyError, ValueError) as e:
        print(f"Error: bad objectives file {obj_path}: {e}",
              file=sys.stderr)
        return 2
    if not args.quiet:
        src = (f"{len(archive.fleet_dirs())} replica archives under "
               f"{archive.archive_dir()}" if args.fleet
               else archive.archive_path())
        sys.stdout.write(format_table(result, src))
    if args.json:
        text = json.dumps(result, indent=1)
        if args.json == "-":
            print(text)
        else:
            with open(args.json, "w") as fp:
                fp.write(text + "\n")
    return 1 if result["violated"] else 0
