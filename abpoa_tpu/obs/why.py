"""`abpoa-tpu why` — the postmortem analyzer: one request, one verdict.

Input is a request id (looked up in the run-report archive, which PR-15
records cross-reference to their trace/dump files), or a direct path to
a per-request Chrome trace (`--trace-dir` output) or a harvested flight-
recorder dump (obs/flight.py). Output is a one-screen causal story:

- header: request id, terminal status, wall, device, when;
- budget attribution: where the wall went (admission wait vs dispatch vs
  unattributed), from the request's span slice;
- the span timeline, indented by containment, attempts marked — the
  worker-pipe crossing is visible as `pool:` spans wrapping `job:` spans
  measured in another process;
- the flight-recorder tail: what the worker was doing when it died (open
  span, last dispatch signature + rung, RSS trend, absorbed faults);
- a verdict line, e.g. "504: 28.1 s of 30 s budget spent in admission
  wait behind a coalesced K=8 group; worker killed mid `dp:jax`
  dispatch, rung Qp=2048/W=256".

This is the layer that turns the chaos scenarios (and the future on-chip
soak, ROADMAP item 3) from survivable into *diagnosable*: every 504/500/
kill can answer "where inside the job did the time go, and what was
running when it died".
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

from . import archive
from .flight import SCHEMA as FLIGHT_SCHEMA

# rung-describing keys rendered from dispatch span args, in display order
_RUNG_KEYS = ("Qp", "W", "K", "R", "P", "N", "rows", "qlen", "sets")


def _fmt_rung(args: Optional[dict]) -> str:
    if not args:
        return ""
    parts = [f"{k}={args[k]}" for k in _RUNG_KEYS if k in args]
    return "/".join(parts)


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "?"
    return f"{v * 1e3:.1f} ms" if v < 1.0 else f"{v:.1f} s"


# --------------------------------------------------------------------------- #
# input resolution                                                            #
# --------------------------------------------------------------------------- #

def load_artifact(path: str) -> Tuple[Optional[dict], Optional[dict]]:
    """-> (trace_doc, dump) from one JSON file, whichever it is."""
    with open(path) as fp:
        doc = json.load(fp)
    if isinstance(doc, dict) and doc.get("schema") == FLIGHT_SCHEMA:
        return None, doc
    if isinstance(doc, dict) and "traceEvents" in doc:
        return doc, None
    raise ValueError(f"{path}: neither a flight dump nor a Chrome trace")


def find_record(rid: str, window: int = 0) -> Optional[dict]:
    """Newest archive record carrying this request id (archive.find_request
    — serve requests and pool jobs both record one per terminal status)."""
    return archive.find_request(rid, window)


def find_fleet_records(rid: str, window: int = 0) -> List[dict]:
    """Every record for this id across replica archives — one per
    delivery attempt when the fleet router failed over or hedged."""
    return archive.find_request_fleet(rid, window)


def pick_terminal(hits: List[dict]) -> Optional[dict]:
    """The record `why` narrates when a request has several (fleet hop):
    the served one if any attempt succeeded, else the last attempt."""
    if not hits:
        return None
    for rec in hits:
        if rec.get("status") == "ok":
            return rec
    return hits[-1]


def fleet_hop_lines(hits: List[dict]) -> List[str]:
    """The hop story: one line per delivery attempt across replicas —
    how a replica death became a retried 200 instead of an outage."""
    lines = [f"fleet hops ({len(hits)} delivery attempts):"]
    for rec in hits:
        lines.append(
            f"  attempt {rec.get('attempt') or 1}: "
            f"replica {rec.get('replica') or '?'}  "
            f"status={rec.get('status')}  "
            f"wall={_fmt_s(rec.get('total_wall_s'))}"
            + (f"  at {rec['ts']}" if rec.get("ts") else ""))
    return lines


# --------------------------------------------------------------------------- #
# analysis                                                                    #
# --------------------------------------------------------------------------- #

def _trace_spans(trace_doc: dict) -> List[dict]:
    return [e for e in trace_doc.get("traceEvents", [])
            if e.get("ph") == "X"]


def _attribution(spans: List[dict]) -> dict:
    """Per-name wall sums (seconds) over the request's spans, plus the
    request envelope: the outermost `request`/`pool_wait` bracket."""
    tot: dict = {}
    for e in spans:
        tot[e["name"]] = tot.get(e["name"], 0.0) + e.get("dur", 0.0) / 1e6
    return tot


def _span_tree_lines(spans: List[dict], limit: int = 24) -> List[str]:
    """The timeline, indented by containment per track (tid). Chrome
    semantics: a span nests under the previous span of the same tid that
    still covers its interval."""
    lines: List[str] = []
    by_tid: dict = {}
    for e in sorted(spans, key=lambda e: (e.get("ts", 0.0),
                                          -e.get("dur", 0.0))):
        tid = e.get("tid", 0)
        stack = by_tid.setdefault(tid, [])
        ts, dur = e.get("ts", 0.0), e.get("dur", 0.0)
        while stack and ts >= stack[-1]:
            stack.pop()
        depth = len(stack)
        stack.append(ts + dur)
        args = e.get("args") or {}
        att = f" [attempt {args['attempt']}]" if args.get("attempt") else ""
        rung = _fmt_rung(args)
        rung = f"  ({rung})" if rung else ""
        lines.append(f"  {'  ' * depth}{e['name']:<24} "
                     f"{_fmt_s(dur / 1e6):>10}  t+{ts / 1e6:.3f}s"
                     f"{att}{rung}")
    if len(lines) > limit:
        lines = lines[:limit] + [f"  ... {len(lines) - limit} more spans "
                                 "(open the trace in Perfetto)"]
    return lines


_DEATH_PHRASES = {
    "killed_deadline": "hard-killed at the job deadline",
    "killed_rss": "hard-killed over the RSS budget",
    "killed_stall": "hard-killed on a stalled heartbeat",
    "crashed": "crashed",
}


def _death_clause(dump: dict) -> str:
    """The kill half of the verdict, from a harvested flight dump."""
    harvest = dump.get("harvest") or {}
    reason = harvest.get("reason", "died")
    reason = _DEATH_PHRASES.get(reason, reason)
    job = dump.get("job") or {}
    open_spans = dump.get("open_spans") or []
    last = dump.get("last_dispatch")
    where = ""
    if open_spans:
        inner = open_spans[-1]
        where = f" mid `{inner['name']}`"
        rung = _fmt_rung(inner.get("args"))
        if not rung and last:
            rung = _fmt_rung(last.get("args"))
        if rung:
            where += f", rung {rung}"
    elif last:
        rung = _fmt_rung(last.get("args"))
        where = (f" between dispatches (last: `{last['name']}`"
                 + (f", rung {rung}" if rung else "") + ")")
    att = job.get("attempt")
    att_s = f" on attempt {att}" if att and att > 1 else ""
    return f"worker {reason}{where}{att_s}"


def verdict(record: Optional[dict], trace_doc: Optional[dict],
            dump: Optional[dict]) -> str:
    """One causal sentence. Status comes from the archive record when we
    have one, else from the dump's harvested death."""
    status = (record or {}).get("status")
    wall = (record or {}).get("total_wall_s")
    deadline = (record or {}).get("deadline_s")
    clauses: List[str] = []
    att = _attribution(_trace_spans(trace_doc)) if trace_doc else {}
    wait = att.get("admission_wait") or att.get("pool_wait")
    # continuous batching (PR 17): a churned request's pickup-time
    # coalesced_k is stale — the record (and its admission_wait span args)
    # carry the group id and the round it actually boarded
    jr = (record or {}).get("join_round")
    jg = (record or {}).get("join_group")
    if jr is None and trace_doc:
        for e in _trace_spans(trace_doc):
            if e["name"] == "admission_wait":
                a = e.get("args") or {}
                if a.get("join_round") is not None:
                    jr, jg = a.get("join_round"), a.get("join_group")
    joined = (f"joined group {jg} at round {jr}"
              if jr is not None else "")
    if status == "timeout":
        head = "504"
        if wait and wall:
            k = None
            for e in _trace_spans(trace_doc):
                if e["name"] == "admission_wait":
                    k = (e.get("args") or {}).get("coalesced_k")
            behind = (f" ({joined})" if joined
                      else f" behind a coalesced K={k} group"
                      if k and k > 1 else "")
            budget = f" of {deadline:g} s budget" if deadline else ""
            clauses.append(f"{wait:.1f} s{budget} spent in admission wait"
                           f"{behind}")
        elif wall is not None:
            clauses.append(f"deadline expired after {_fmt_s(wall)}"
                           + (f" ({joined})" if joined else ""))
    elif status == "ok":
        head = "ok"
        clauses.append(f"served in {_fmt_s(wall)}"
                       + (f" ({_fmt_s(wait)} of it queued)"
                          if wait and wall and wait > 0.5 * wall else "")
                       + (f" ({joined})" if joined else ""))
    elif status == "poisoned" or status == "quarantined":
        head = "400"
        clauses.append("poisoned set rejected at the quarantine boundary")
    elif status in ("error", "poison"):
        head = "500"
        if not dump:
            clauses.append("unclassified failure (see faults)")
    elif status is None and dump is not None:
        head = "killed"
    else:
        head = status or "?"
    if dump is not None and (dump.get("harvest")
                             or (dump.get("job") or {}).get(
                                 "status", "").startswith("died")):
        clauses.append(_death_clause(dump))
    if not clauses:
        clauses.append("no causal signal recorded (trace/dump missing?)")
    return f"{head}: " + "; ".join(clauses)


# --------------------------------------------------------------------------- #
# rendering                                                                   #
# --------------------------------------------------------------------------- #

def render_why(record: Optional[dict], trace_doc: Optional[dict],
               dump: Optional[dict], ref: str = "") -> str:
    lines: List[str] = []
    rid = ((record or {}).get("request_id")
           or ((dump or {}).get("job") or {}).get("rid")
           or ((dump or {}).get("harvest") or {}).get("request_id")
           or ref)
    head = f"why {rid}"
    if record:
        head += (f"  status={record.get('status')}"
                 f"  wall={_fmt_s(record.get('total_wall_s'))}"
                 + (f"  device={record.get('device')}"
                    if record.get("device") else "")
                 + (f"  replica={record.get('replica')}"
                    if record.get("replica") else "")
                 + (f"  attempt={record.get('attempt')}"
                    if (record.get("attempt") or 1) > 1 else "")
                 + (f"  at {record.get('ts')}" if record.get("ts") else ""))
    lines.append(head)
    # sharded route (PR 19): the record prices the whole mesh — name the
    # global K cap and the mesh it was spread over
    if record and record.get("mesh"):
        lines.append(f"route: {record.get('route') or 'sharded'} "
                     f"K={record.get('k_cap') or '?'} "
                     f"over mesh={record['mesh']}")
        # shard-skew attribution (obs/rounds.py via serve account()):
        # which shard gated the request's last sharded round, and by how
        # much the mesh was out of level
        if record.get("slowest_shard") is not None:
            wall = record.get("round_wall_ms")
            lines.append(
                f"slowest shard: {record['slowest_shard']} "
                f"(skew {record.get('shard_skew', 1.0):.2f}x"
                + (f", round wall {wall:.2f} ms" if wall else "")
                + ")")
    lines.append("")
    lines.append("verdict: " + verdict(record, trace_doc, dump))

    if trace_doc:
        spans = _trace_spans(trace_doc)
        att = _attribution(spans)
        if att:
            lines.append("")
            total = (record or {}).get("total_wall_s")
            lines.append("time attribution (span wall sums):")
            for name, w in sorted(att.items(), key=lambda kv: -kv[1])[:8]:
                share = (f" {100 * w / total:>5.1f}%"
                         if total else "")
                lines.append(f"  {name:<24} {_fmt_s(w):>10}{share}")
        if spans:
            lines.append("")
            lines.append(f"span timeline ({len(spans)} spans):")
            lines.extend(_span_tree_lines(spans))

    if dump:
        lines.append("")
        job = dump.get("job") or {}
        lines.append(f"flight recorder (worker pid {dump.get('pid')}, "
                     f"label {dump.get('label') or '?'}, "
                     f"{dump.get('beats', 0)} beats):")
        if job:
            lines.append(f"  job: {job.get('kind')} {job.get('label') or ''}"
                         f" rid={job.get('rid')} attempt={job.get('attempt')}"
                         f" status={job.get('status')}".rstrip())
        harvest = dump.get("harvest")
        if harvest:
            det = f" ({harvest['detail']})" if harvest.get("detail") else ""
            lines.append(f"  harvested: {harvest.get('reason')}{det}")
        for sp in dump.get("open_spans") or []:
            rung = _fmt_rung(sp.get("args"))
            lines.append(f"  open span at death: `{sp['name']}` "
                         f"[{sp['cat']}] running {_fmt_s(sp['elapsed_s'])}"
                         + (f"  rung {rung}" if rung else ""))
        last = dump.get("last_dispatch")
        if last:
            rung = _fmt_rung(last.get("args"))
            lines.append(f"  last dispatch: `{last['name']}` "
                         f"{_fmt_s(last.get('dur_s'))}"
                         + (f"  rung {rung}" if rung else ""))
        rss = dump.get("rss") or []
        if rss:
            first, peak = rss[0][1], max(r[1] for r in rss)
            lines.append(f"  rss: {first / 1e6:.0f} MB -> "
                         f"{rss[-1][1] / 1e6:.0f} MB at death "
                         f"(peak {peak / 1e6:.0f} MB over "
                         f"{len(rss)} beats)")
        faults = dump.get("faults") or []
        if faults:
            lines.append(f"  absorbed faults ({len(faults)} recent):")
            for rec in faults[-5:]:
                lines.append(f"    t+{rec.get('t_s', 0):.2f}s "
                             f"{rec.get('kind')}"
                             + (f" -> {rec['action']}"
                                if rec.get("action") else ""))

    if record:
        refs = []
        if record.get("trace_file"):
            refs.append(f"trace: {record['trace_file']}")
        if record.get("dump_file"):
            refs.append(f"dump: {record['dump_file']}")
        if refs:
            lines.append("")
            lines.append("artifacts: " + "  ".join(refs))
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------- #
# CLI                                                                         #
# --------------------------------------------------------------------------- #

def why_main(argv) -> int:
    """`abpoa-tpu why <request-id | trace.json | dump.json>` — rc 0 on a
    rendered verdict, 2 when the id/file resolves to nothing."""
    ap = argparse.ArgumentParser(
        prog="abpoa-tpu why",
        description="postmortem analyzer: render one request's span tree "
                    "+ flight-recorder tail into a causal verdict "
                    "(why was this a 504/500/kill?)")
    ap.add_argument("what",
                    help="request id (X-Abpoa-Request-Id / archive "
                         "request_id), or a path to a per-request trace "
                         "or harvested flight dump")
    ap.add_argument("--archive-dir", default=None, metavar="DIR",
                    help="archive directory for id lookup "
                         "[ABPOA_TPU_ARCHIVE_DIR or "
                         "~/.cache/abpoa_tpu/reports]")
    ap.add_argument("--window", type=int, default=0, metavar="N",
                    help="newest N archive records to search [all]")
    ap.add_argument("--fleet", action="store_true",
                    help="search every replica archive (replica-* subdirs "
                         "of the archive dir) and narrate the delivery "
                         "hops of a failed-over/hedged request; ids that "
                         "miss the plain archive fall back to the fleet "
                         "search automatically")
    args = ap.parse_args(argv)
    if args.archive_dir:
        os.environ["ABPOA_TPU_ARCHIVE_DIR"] = args.archive_dir
    record = trace_doc = dump = None
    hops: List[dict] = []
    if os.path.exists(args.what):
        try:
            trace_doc, dump = load_artifact(args.what)
        except (OSError, ValueError) as e:
            print(f"Error: {e}", file=sys.stderr)
            return 2
        rid = (((dump or {}).get("job") or {}).get("rid")
               or ((dump or {}).get("harvest") or {}).get("request_id"))
        if not rid and trace_doc:
            for e in trace_doc.get("traceEvents", []):
                rid = (e.get("args") or {}).get("rid") or \
                    (e.get("args") or {}).get("request_id")
                if rid:
                    break
        if rid:
            record = find_record(rid, args.window)
    else:
        if args.fleet:
            hops = find_fleet_records(args.what, args.window)
            record = pick_terminal(hops)
        else:
            record = find_record(args.what, args.window)
            if record is None:
                # a fleet request's records live in replica subdirs the
                # plain lookup never sees — resolve across them before
                # giving up
                hops = find_fleet_records(args.what, args.window)
                record = pick_terminal(hops)
        if record is None:
            print(f"Error: request id {args.what!r} not found in the "
                  f"archive under {archive.archive_dir()} (and it is not "
                  "a file)", file=sys.stderr)
            return 2
    # pull the cross-referenced artifacts the archive record names
    if record is not None:
        for key, slot in (("trace_file", "trace"), ("dump_file", "dump")):
            path = record.get(key)
            if not path or not os.path.exists(path):
                continue
            try:
                t, d = load_artifact(path)
            except (OSError, ValueError):
                continue
            if slot == "trace" and trace_doc is None:
                trace_doc = t
            if slot == "dump" and dump is None:
                dump = d
    sys.stdout.write(render_why(record, trace_doc, dump, ref=args.what))
    if len(hops) > 1:
        # more than one delivery attempt: name the replica hop (the
        # failover/hedge explanation the fleet chaos proof asserts on)
        sys.stdout.write("\n" + "\n".join(fleet_hop_lines(hops)) + "\n")
    elif record is not None and (record.get("attempt") or 1) > 1:
        # a SIGKILLed replica archives nothing for the lost attempt;
        # the surviving record's attempt number still tells the story
        sys.stdout.write(
            f"\nfleet: delivered on attempt {record['attempt']} by "
            f"replica {record.get('replica') or '?'} — the earlier "
            "attempt left no archive record (its replica died "
            "mid-request; the router failed the request over)\n")
    return 0
