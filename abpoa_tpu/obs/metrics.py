"""Fleet-grade metric registry: typed metrics + Prometheus exposition.

Everything the RunReport (report.py) knows dies with the run; this module
is the long-lived layer a persistent `abpoa-tpu serve` process (ROADMAP
item 1) reports itself through: process-cumulative counters, gauges, and
streaming-quantile histograms, rendered in the Prometheus text exposition
format — either as a textfile exporter (`--metrics FILE`, atomic rename,
node_exporter-compatible) or over a stdlib-only HTTP endpoint
(`--metrics-port N`).

Three metric types:

- Counter: monotonic totals, labeled (`abpoa_reads_total{backend="jax"}`).
- Gauge: last-written values (`abpoa_breaker_open{backend="jax"}`).
- Histogram: a bounded log-bucket sketch (`LogSketch`) — fixed geometric
  buckets over [LO, HI), so p50/p95/p99 over millions of observations
  cost O(1) memory and stay within a declared relative error
  (`LogSketch.RELATIVE_ERROR`), unlike the old capped-list percentile
  path that silently lied past READS_CAP. Sketches are mergeable
  (bucket-wise addition), the property cross-run aggregation needs.

Publication: obs/report.py mirrors its hot-path hooks here (counter
names -> labeled Prometheus families via `publish_counter`, phase exits
via `publish_phase`, per-read records via `publish_read`); resilience/
publishes breaker state directly. Every publication is a host-side dict
or array update — the obs overhead contract (no device syncs, no
allocation beyond the bucket array) holds; `ABPOA_TPU_METRICS=0` or
`set_enabled(False)` is the A/B kill switch.

Rates (reads/s, cell-updates/s, MFU) are computed at render time from
counter deltas between consecutive renders, so a periodic exporter
(`start_textfile_exporter`) yields live gauges the `abpoa-tpu top`
dashboard can poll.
"""
from __future__ import annotations

import math
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

_ENABLED = os.environ.get("ABPOA_TPU_METRICS", "1") not in ("0", "off")

NAMESPACE = "abpoa"

# serializes the mutate paths (Counter.inc / Gauge.set / sketch.observe):
# read-modify-write under the GIL can interleave between threads, and
# `abpoa-tpu serve` is the first concurrent publisher (N handler threads
# + workers). One process-wide RLock — uncontended acquire is ~100 ns
# against per-event work in the µs-ms range; render paths keep their
# existing snapshot-under-GIL strategy and never hold this lock.
_MUT = threading.RLock()


def enabled() -> bool:
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Publication kill switch (the overhead guard's control arm)."""
    global _ENABLED
    _ENABLED = bool(flag)


# --------------------------------------------------------------------------- #
# streaming-quantile sketch                                                   #
# --------------------------------------------------------------------------- #

class LogSketch:
    """Fixed-bucket log histogram over (LO, HI): a bounded, mergeable
    quantile sketch.

    Bucket i covers [LO*G^i, LO*G^(i+1)); a quantile query walks the
    cumulative counts and answers the geometric midpoint of the target
    bucket, clamped to the exact observed [min, max]. Worst-case relative
    error is sqrt(G)-1 (~2.5% at G=1.05) for in-range values — declared
    as RELATIVE_ERROR with margin. Out-of-range values clamp into the
    edge buckets but min/max stay exact, so the clamp keeps even those
    honest at the distribution edges.
    """

    LO = 1e-6          # 1 microsecond
    HI = 1e4           # ~2.8 hours
    GROWTH = 1.05
    N_BUCKETS = int(math.ceil(math.log(HI / LO) / math.log(GROWTH)))  # ~472
    RELATIVE_ERROR = 0.05

    __slots__ = ("counts", "count", "sum", "min", "max")

    _LOG_G = math.log(GROWTH)
    _LOG_LO = math.log(LO)

    def __init__(self) -> None:
        self.counts = [0] * self.N_BUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float) -> None:
        with _MUT:
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            self.count += 1
            self.sum += v
            if v <= self.LO:
                i = 0
            else:
                i = int((math.log(v) - self._LOG_LO) / self._LOG_G)
                if i >= self.N_BUCKETS:
                    i = self.N_BUCKETS - 1
            self.counts[i] += 1

    def merge(self, other: "LogSketch") -> None:
        """Bucket-wise merge (cross-run / cross-shard aggregation)."""
        with _MUT:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
            self.count += other.count
            self.sum += other.sum
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile estimate within RELATIVE_ERROR."""
        if self.count == 0:
            return None
        rank = max(1, min(self.count, math.ceil(q * self.count)))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                mid = self.LO * self.GROWTH ** (i + 0.5)
                return min(self.max, max(self.min, mid))
        return self.max

    def bucket_upper_bounds(self):
        """(upper_bound_seconds, cumulative_count) for every non-empty
        bucket — the Prometheus histogram series (cumulative `le`).
        Snapshots the bucket array first so a concurrent observe() from
        the run thread cannot produce a non-cumulative series."""
        out = []
        acc = 0
        for i, c in enumerate(list(self.counts)):
            if c:
                acc += c
                out.append((self.LO * self.GROWTH ** (i + 1), acc))
        return out


# --------------------------------------------------------------------------- #
# metric families                                                             #
# --------------------------------------------------------------------------- #

def _fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    esc = lambda v: str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")  # noqa: E731
    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in labels) + "}"


class Counter:
    TYPE = "counter"

    __slots__ = ("name", "help", "values")

    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self.values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, n: float = 1, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with _MUT:
            self.values[key] = self.values.get(key, 0) + n

    def value(self, **labels) -> float:
        return self.values.get(tuple(sorted(labels.items())), 0)

    def total(self) -> float:
        # atomic snapshot first: summing the live view from the exporter
        # thread would raise if the run thread inserts a key mid-sum
        return sum(list(self.values.values()))

    def render(self, out: List[str]) -> None:
        # list() snapshots atomically under the GIL: the exporter thread
        # renders while the run thread inserts new label keys, and keys
        # are never deleted, so a snapshot of items is always consistent
        for key, v in sorted(list(self.values.items())):
            out.append(f"{self.name}{_fmt_labels(key)} {_num(v)}")


class Gauge(Counter):
    TYPE = "gauge"
    __slots__ = ()

    def set(self, v: float, **labels) -> None:
        with _MUT:
            self.values[tuple(sorted(labels.items()))] = v


class Histogram:
    """One LogSketch, exposed in the Prometheus histogram format
    (cumulative `le` buckets + `_sum` + `_count`)."""

    TYPE = "histogram"

    __slots__ = ("name", "help", "sketch")

    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self.sketch = LogSketch()

    def observe(self, v: float) -> None:
        self.sketch.observe(v)

    def quantile(self, q: float) -> Optional[float]:
        return self.sketch.quantile(q)

    def render(self, out: List[str]) -> None:
        # +Inf and _count derive from the same bucket snapshot the `le`
        # series used: a frame rendered mid-observe stays self-consistent
        # (the lint checks exactly that), at worst one observation stale
        buckets = self.sketch.bucket_upper_bounds()
        total = buckets[-1][1] if buckets else 0
        for ub, acc in buckets:
            out.append(f'{self.name}_bucket{{le="{ub:.9g}"}} {acc}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        out.append(f"{self.name}_sum {_num(self.sketch.sum)}")
        out.append(f"{self.name}_count {total}")


def _num(v: float) -> str:
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


# --------------------------------------------------------------------------- #
# registry                                                                    #
# --------------------------------------------------------------------------- #

class MetricsRegistry:
    """Process-global family store + exposition renderer.

    `collectors` are callbacks run at render time (device identity,
    trace-drop gauges — values that are cheap to read but wasteful to
    push on every event). Rate gauges (reads/s, CUPS, MFU) are derived
    from counter deltas between consecutive renders.
    """

    def __init__(self) -> None:
        self._families: Dict[str, object] = {}
        self._order: List[str] = []
        self.collectors: List[Callable[["MetricsRegistry"], None]] = []
        self._lock = threading.Lock()
        # rate-gauge origin: first render averages over registry lifetime,
        # later renders over the inter-render interval (live rates)
        self._prev_rates: Tuple[float, float, float, float, float] = (
            time.perf_counter(), 0.0, 0.0, 0.0, 0.0)
        self.created = time.time()

    # ------------------------------------------------------------- families
    def _family(self, cls, name: str, help_: str):
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = cls(name, help_)
                    self._families[name] = fam
                    self._order.append(name)
        return fam

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._family(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._family(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "") -> Histogram:
        return self._family(Histogram, name, help_)

    def get(self, name: str):
        return self._families.get(name)

    def register_collector(self, fn: Callable) -> None:
        if fn not in self.collectors:
            self.collectors.append(fn)

    # ------------------------------------------------------------- rendering
    def _update_rate_gauges(self) -> None:
        """reads/s, cell-updates/s and MFU from counter deltas between
        consecutive renders — live gauges for a polling exporter, whole-
        process averages on a one-shot render."""
        now = time.perf_counter()
        reads = _fam_total(self, "abpoa_reads_total")
        cells = _fam_total(self, "abpoa_dp_cells_total")
        ops = _fam_total(self, "abpoa_dp_cell_ops_total")
        map_reads = _fam_total(self, "abpoa_map_reads_total")
        prev = self._prev_rates
        self._prev_rates = (now, reads, cells, ops, map_reads)
        dt = now - prev[0]
        if dt <= 0:
            return
        g = self.gauge("abpoa_reads_per_second",
                       "Read throughput over the last exporter interval")
        g.set(round((reads - prev[1]) / dt, 3))
        if map_reads > 0:
            g = self.gauge("abpoa_map_reads_per_second",
                           "Map-workload read throughput over the last "
                           "exporter interval")
            prev_map = prev[4] if len(prev) > 4 else 0.0
            g.set(round((map_reads - prev_map) / dt, 3))
        g = self.gauge("abpoa_cell_updates_per_second",
                       "DP cell-updates/s over the last exporter interval "
                       "(the AnySeq/GPU throughput metric)")
        g.set(round((cells - prev[2]) / dt, 1))
        peak = _fam_total(self, "abpoa_device_peak_ops_per_second")
        if peak > 0:
            g = self.gauge("abpoa_mfu_ratio",
                           "Model FLOPs utilization estimate over the last "
                           "exporter interval (DP int-ops vs device peak)")
            g.set(round((ops - prev[3]) / dt / peak, 6))

    def _update_quantile_gauges(self) -> None:
        for base, help_ in (
                ("abpoa_read_wall_seconds",
                 "Sketch-estimated per-read wall quantiles "
                 "(textfile-exporter convenience for `top`)"),
                ("abpoa_serve_request_seconds",
                 "Sketch-estimated request-latency quantiles "
                 "(textfile-exporter convenience for `top`)")):
            h = self._families.get(base)
            if h is None or h.sketch.count == 0:
                continue
            g = self.gauge(base + "_quantile", help_)
            for q, label in ((0.5, "0.5"), (0.95, "0.95"), (0.99, "0.99")):
                g.set(round(h.quantile(q), 9), quantile=label)

    def render(self) -> str:
        """The Prometheus text exposition (format version 0.0.4)."""
        for fn in list(_GLOBAL_COLLECTORS) + list(self.collectors):
            try:
                fn(self)
            except Exception:
                pass
        self._update_rate_gauges()
        self._update_quantile_gauges()
        out: List[str] = []
        with self._lock:
            names = list(self._order)
        for name in names:
            fam = self._families[name]
            if fam.help:
                out.append(f"# HELP {name} {fam.help}")
            out.append(f"# TYPE {name} {fam.TYPE}")
            fam.render(out)
        return "\n".join(out) + "\n"


def _fam_total(reg: MetricsRegistry, name: str) -> float:
    fam = reg.get(name)
    return fam.total() if isinstance(fam, Counter) else 0.0


_REGISTRY = MetricsRegistry()

# collectors that survive reset_registry() (module-lifetime publishers:
# obs/report.py's device/trace gauges)
_GLOBAL_COLLECTORS: List[Callable] = []


def register_global_collector(fn: Callable) -> None:
    if fn not in _GLOBAL_COLLECTORS:
        _GLOBAL_COLLECTORS.append(fn)


def registry() -> MetricsRegistry:
    return _REGISTRY


def reset_registry() -> MetricsRegistry:
    """Fresh registry (tests; a served process never resets)."""
    global _REGISTRY
    _REGISTRY = MetricsRegistry()
    return _REGISTRY


# --------------------------------------------------------------------------- #
# publication hooks (called from obs/report.py and resilience/)               #
# --------------------------------------------------------------------------- #

# RunReport counter name -> (family, help, label name). The run report
# keeps the full dotted namespace; the registry keeps the curated fleet
# families. A prefix absent here stays report-only by design.
_PREFIX_FAMILIES = {
    "dispatch": ("abpoa_dispatches_total",
                 "DP kernel dispatches by backend", "backend"),
    "fallback": ("abpoa_fallbacks_total",
                 "Degraded-path falls by reason", "reason"),
    "reroute": ("abpoa_reroutes_total",
                "Device-ineligible config reroutes by reason", "reason"),
    "faults": ("abpoa_faults_total",
               "Absorbed dispatch/input faults by kind", "kind"),
    "inject": ("abpoa_injected_faults_total",
               "Fault-injector firings by kind", "kind"),
}

# scheduler decisions carry TWO labels — the route kind plus the
# categorical decision code (`scheduler.<kind>.<code>` report counters),
# so crossover-serial is distinguishable from explicit/ineligible-serial
# in the ledger's route mix (ISSUE 20 small fix)
_SCHED_FAMILY = ("abpoa_scheduler_routes_total",
                 "Batch/serve dispatch route decisions by route and "
                 "decision reason")

_EXACT_FAMILIES = {
    "compile.hits": ("abpoa_compile_hits_total",
                     "Jit dispatches served from a compile cache"),
    "compile.misses": ("abpoa_compile_misses_total",
                       "Jit dispatches that compiled (XLA or persistent-"
                       "cache load)"),
    "quarantine.sets": ("abpoa_quarantined_sets_total",
                        "Read sets quarantined at the -l/batch boundary"),
    "watchdog.timeouts": ("abpoa_watchdog_fires_total",
                          "Dispatch watchdog deadline expiries"),
    "admission.demote": ("abpoa_admission_demotions_total",
                         "Memory-admission demotions to the host kernel"),
    "admission.chunk": ("abpoa_admission_chunks_total",
                        "Memory-admission lockstep group splits"),
    "breaker.short_circuit": ("abpoa_breaker_short_circuits_total",
                              "Dispatches short-circuited by an open "
                              "circuit breaker"),
    "lockstep.groups": ("abpoa_lockstep_groups_total",
                        "Lockstep multi-set device dispatch groups"),
    "lockstep.chunks": ("abpoa_lockstep_chunks_total",
                        "Lockstep dispatch rounds/chunks (all-device "
                        "chunks, or split-driver DP rounds)"),
    "lockstep.drain_chunks": ("abpoa_lockstep_drain_chunks_total",
                              "Lockstep rounds entered with at least one "
                              "set already finished (divergence drain)"),
    "lockstep.split_bt_fallback": ("abpoa_lockstep_split_bt_fallbacks_total",
                                   "Split-lockstep sets sent to the "
                                   "sequential path by a device backtrack "
                                   "divergence"),
    "lockstep.joins": ("abpoa_lockstep_joins_total",
                       "Requests that joined an in-flight lockstep group "
                       "at a round boundary (continuous batching)"),
    "lockstep.early_retires": ("abpoa_lockstep_early_retires_total",
                               "Lanes retired from an in-flight lockstep "
                               "group before the group ended (result "
                               "returned early, slot freed for joiners)"),
    "lockstep.evictions": ("abpoa_lockstep_evictions_total",
                           "Lanes evicted from an in-flight lockstep group "
                           "at a round boundary (deadline expired)"),
    "dp.dispatches": ("abpoa_dp_dispatches_total", "DP kernel dispatches"),
    "dp.cells": ("abpoa_dp_cells_total", "DP cells computed"),
    "dp.cell_ops": ("abpoa_dp_cell_ops_total",
                    "Estimated integer ops over DP cells (MFU numerator)"),
    # process-pool supervisor (parallel/pool.py)
    "pool.restarts": ("abpoa_pool_restarts_total",
                      "Pool worker processes respawned after a death or "
                      "hard kill"),
    "pool.kills": ("abpoa_pool_kills_total",
                   "Supervisor-initiated hard SIGKILLs (job deadline, "
                   "RSS budget, stalled heartbeat)"),
    "pool.requeues": ("abpoa_pool_requeues_total",
                      "Jobs requeued onto a fresh worker after their "
                      "worker died (exactly once per job)"),
    "pool.poison_jobs": ("abpoa_pool_poison_jobs_total",
                         "Jobs quarantined after killing their worker "
                         "twice"),
    "pool.worker_crashes": ("abpoa_pool_worker_crashes_total",
                            "Worker processes that died on their own "
                            "(signal or unexpected exit)"),
    "pool.worker_xla_compiles": ("abpoa_pool_worker_xla_compiles_total",
                                 "True XLA compiles inside pool workers "
                                 "(persistent-cache misses — the "
                                 "recompile-burst signal)"),
    "pool.worker_cache_loads": ("abpoa_pool_worker_cache_loads_total",
                                "Pool worker compile-cache loads served "
                                "by the persistent XLA cache"),
    # PR 15: request tracing + worker flight recorder
    "pool.flight_dumps": ("abpoa_pool_flight_dumps_total",
                          "Flight-recorder dumps harvested from killed/"
                          "crashed pool workers"),
    "serve.traces": ("abpoa_serve_traces_total",
                     "Per-request Chrome traces written to --trace-dir"),
    # PR 18: fixed-graph map workload (parallel/map_driver.py)
    "map.reads": ("abpoa_map_reads_total",
                  "Reads mapped against a static graph (map workload)"),
    "map.rounds": ("abpoa_map_rounds_total",
                   "Map-driver dispatch rounds (one vmapped DP chunk per "
                   "round, zero fusion barrier)"),
    "map.joins": ("abpoa_map_joins_total",
                  "Reads that boarded a map round via the streaming hook "
                  "(continuous batching at DP-round granularity)"),
}

_BREAKER_PREFIXES = {
    "breaker.failures": ("abpoa_breaker_failures_total",
                         "Classified dispatch failures by backend"),
    "breaker.open": ("abpoa_breaker_opens_total",
                     "Circuit-breaker open events by backend"),
    "breaker.half_open": ("abpoa_breaker_half_open_probes_total",
                          "Cooldown-expiry half-open probe dispatches by "
                          "backend"),
    "breaker.reclose": ("abpoa_breaker_recloses_total",
                        "Circuit-breaker reclose events (successful "
                        "half-open probes) by backend"),
    "breaker.probe_fail": ("abpoa_breaker_probe_failures_total",
                           "Half-open probes that failed and reopened "
                           "the breaker, by backend"),
}


def publish_counter(name: str, n: int) -> None:
    """Mirror one RunReport counter increment into the fleet registry."""
    if not _ENABLED:
        return
    exact = _EXACT_FAMILIES.get(name)
    if exact is not None:
        _REGISTRY.counter(*exact).inc(n)
        return
    head, _, rest = name.partition(".")
    if head == "scheduler":
        kind, _, code = rest.partition(".")
        _REGISTRY.counter(*_SCHED_FAMILY).inc(
            n, route=kind, reason=code or "unspecified")
        return
    fam = _PREFIX_FAMILIES.get(head)
    if fam is not None:
        _REGISTRY.counter(fam[0], fam[1]).inc(n, **{fam[2]: rest})
        return
    for pref, (fname, fhelp) in _BREAKER_PREFIXES.items():
        if name.startswith(pref + "."):
            _REGISTRY.counter(fname, fhelp).inc(
                n, backend=name[len(pref) + 1:])
            return


def publish_phase(name: str, wall_s: float) -> None:
    if _ENABLED:
        _REGISTRY.counter(
            "abpoa_phase_wall_seconds_total",
            "Wall seconds by pipeline phase").inc(wall_s, phase=name)


# one definition site for the per-read families: publish_read and
# publish_read_aggregate must create them with identical name+help
# (first creation wins, so a drift would make the exposition text depend
# on which publisher ran first)
_READS_FAMILY = ("abpoa_reads_total",
                 "Reads aligned, by the backend that ran them")
_READ_FALLBACKS_FAMILY = ("abpoa_read_fallbacks_total",
                          "Reads that ran on a fallback path, by reason")
_READ_WALL_FAMILY = ("abpoa_read_wall_seconds",
                     "Per-read wall seconds (log-bucket sketch, "
                     f"~{int(LogSketch.RELATIVE_ERROR * 100)}% quantile "
                     "tolerance)")


def publish_read(wall_s: float, backend: str,
                 fallback: Optional[str]) -> None:
    if not _ENABLED:
        return
    _REGISTRY.counter(*_READS_FAMILY).inc(1, backend=backend)
    if fallback:
        _REGISTRY.counter(*_READ_FALLBACKS_FAMILY).inc(1, reason=fallback)
    _REGISTRY.histogram(*_READ_WALL_FAMILY).observe(wall_s)


def publish_read_aggregate(backends: Dict[str, int],
                           fallbacks: Dict[str, int],
                           sketch: LogSketch) -> None:
    """Bulk form of publish_read for a pool worker's per-job delta:
    backend/fallback count increments plus a sketch bucket merge, so the
    exposition matches what per-read publishes would have produced —
    including reads past the worker's raw-record cap."""
    if not _ENABLED:
        return
    for b, n in backends.items():
        if n > 0:
            _REGISTRY.counter(*_READS_FAMILY).inc(n, backend=b)
    for r, n in fallbacks.items():
        if n > 0:
            _REGISTRY.counter(*_READ_FALLBACKS_FAMILY).inc(n, reason=r)
    if sketch.count:
        _REGISTRY.histogram(*_READ_WALL_FAMILY).sketch.merge(sketch)


def publish_run_start() -> None:
    if _ENABLED:
        _REGISTRY.counter("abpoa_runs_total", "Runs started").inc(1)


def set_breaker_state(backend: str, open_: bool) -> None:
    if _ENABLED:
        _REGISTRY.gauge(
            "abpoa_breaker_open",
            "Circuit-breaker state by backend (1 = open/demoted)").set(
            1 if open_ else 0, backend=backend)


_ROUTE_KINDS = ("serial", "pool", "lockstep", "hybrid", "map", "sharded")


def publish_noop_fraction(ewma: float) -> None:
    """Lockstep idle-lane divergence EWMA (the scheduler's K-cap input)."""
    if _ENABLED:
        _REGISTRY.gauge(
            "abpoa_lockstep_noop_fraction",
            "EWMA of the lockstep idle-lane fraction (divergence; feeds "
            "the scheduler's sub-batch K cap)").set(ewma)


def publish_lane_occupancy(ewma: float) -> None:
    """Measured lockstep lane occupancy EWMA (live lanes / group capacity,
    fed per round by the split driver's lane table). Under churn this stays
    near 1.0 — the continuous-batching gate compares it against the static
    baseline's (1 - noop EWMA)."""
    if _ENABLED:
        _REGISTRY.gauge(
            "abpoa_lockstep_lane_occupancy",
            "EWMA of measured lockstep lane occupancy (live lanes over "
            "group capacity, per round)").set(ewma)


def publish_map_round(reads: int, occ: float) -> None:
    """One map-driver round: reads dispatched this round and the round's
    lane occupancy (lanes over the K cap — every round boundary is a
    join/retire point, so this gauge IS the map stream's fullness)."""
    if not _ENABLED:
        return
    _REGISTRY.gauge(
        "abpoa_map_lane_occupancy",
        "Lane occupancy of the last map-driver round (dispatched lanes "
        "over the group's K cap)").set(occ)
    _REGISTRY.gauge(
        "abpoa_map_round_reads",
        "Reads dispatched in the last map-driver round").set(reads)


def publish_mesh(n: int, platform: str) -> None:
    """Mesh inventory of the sharded route: device count and platform of
    the lane mesh the last sharded dispatch spanned (also set at serve
    start, so /healthz and `top` agree on the mesh shape)."""
    if not _ENABLED:
        return
    _REGISTRY.gauge(
        "abpoa_mesh_devices",
        "Devices in the sharded route's lane mesh").set(n)
    _REGISTRY.gauge(
        "abpoa_mesh_platform_info",
        "Mesh platform marker (1 = the labelled platform backs the "
        "mesh)").set(1, platform=platform)


def publish_shard_occupancy(shard_i: int, occ: float) -> None:
    """Per-shard lane occupancy of the last sharded round: live lanes over
    the shard's K/mesh slice. Padding lanes are born finished, so trailing
    shards of a partly-filled global batch read < 1.0 here while the
    leading shards read 1.0 — the skew IS the repack quality signal."""
    if _ENABLED:
        _REGISTRY.gauge(
            "abpoa_shard_lane_occupancy",
            "Lane occupancy per mesh shard in the last sharded round "
            "(live lanes over the per-shard slice)").set(
            occ, shard=str(shard_i))


def publish_round(route: str, wall_s: float, lanes: int,
                  k_cap: int) -> None:
    """One driver round sealed (obs/rounds.py): the round-wall histogram
    the TPU soak reads sustained round cadence from, plus last-round
    lane gauges for `top`."""
    if not _ENABLED:
        return
    _REGISTRY.histogram(
        "abpoa_round_wall_seconds",
        "Wall seconds per lockstep/sharded/map driver round (log-bucket "
        "sketch)").observe(wall_s)
    _REGISTRY.gauge(
        "abpoa_round_lanes",
        "Live lanes in the last driver round").set(lanes)


def publish_shard_skew(ratio: float, straggler: int,
                       walls: Dict[int, float]) -> None:
    """Last sharded round's skew verdict (obs/rounds.py): max/min
    live-shard ratio, the straggler shard id (the max-live shard whose
    estimated wall IS the measured fused dispatch wall), and per-shard
    wall estimates."""
    if not _ENABLED:
        return
    _REGISTRY.gauge(
        "abpoa_shard_skew_ratio",
        "Max/min live-lane ratio across mesh shards in the last sharded "
        "round (1.0 = perfectly level)").set(round(ratio, 6))
    _REGISTRY.gauge(
        "abpoa_shard_straggler",
        "Shard id that gated the last sharded round (max live "
        "lanes)").set(straggler)
    g = _REGISTRY.gauge(
        "abpoa_shard_round_wall_seconds",
        "Estimated per-shard wall of the last sharded round (dispatch "
        "wall attributed by live lanes; the straggler's estimate is the "
        "measured wall)")
    for i, w in walls.items():
        g.set(round(w, 9), shard=str(i))


def publish_join_wait(wait_s: float) -> None:
    """Queue-to-board latency of one continuous-batching join: arrival to
    the round boundary that admitted it into the in-flight group."""
    if _ENABLED:
        _REGISTRY.histogram(
            "abpoa_lockstep_join_wait_seconds",
            "Wait from request arrival to joining an in-flight lockstep "
            "group (continuous batching)").observe(wait_s)


def publish_route(route) -> None:
    """Scheduler decision gauges for `top`: the last planned route (one-hot
    over route kinds) and its lockstep K cap."""
    if not _ENABLED:
        return
    for kind in _ROUTE_KINDS:
        _REGISTRY.gauge(
            "abpoa_scheduler_route",
            "Last planned batch/serve route (1 = selected)").set(
            1 if route.kind == kind else 0, route=kind)
    _REGISTRY.gauge(
        "abpoa_scheduler_k_cap",
        "Lockstep sub-batch K cap of the last planned route").set(
        route.k_cap)


def publish_batch_progress(done: int, total: Optional[int] = None) -> None:
    """Live -l/msa_batch progress for the `top` dashboard: sets completed
    vs total in the current batch run. Single definition site — the CLI
    runner and pyapi.msa_batch both publish through here, with identical
    semantics (a quarantined set counts as completed: the batch moved
    past it)."""
    if not _ENABLED:
        return
    _REGISTRY.gauge(
        "abpoa_batch_sets_done",
        "Read sets completed in the current -l/batch run").set(done)
    if total is not None:
        _REGISTRY.gauge(
            "abpoa_batch_sets",
            "Read sets in the current -l/batch run").set(total)


def bump_batch_set_done() -> None:
    """Count one more set as completed in the current batch run. A set
    is done once it has a final disposition — a result OR a quarantine:
    the batch moved past it either way. The count lives in the gauge
    itself, so every caller shares one definition of 'done'."""
    if not _ENABLED:
        return
    g = _REGISTRY.gauge(
        "abpoa_batch_sets_done",
        "Read sets completed in the current -l/batch run")
    with _MUT:  # read-modify-write spans two calls (RLock re-enters)
        g.set(g.value() + 1)


# ------------------------------------------------------------- serve hooks

def publish_serve_request(status: str, wall_s: float) -> None:
    """One terminal serve-request disposition: `status` is the admission/
    execution verdict (ok | rejected | poisoned | timeout | draining |
    error), `wall_s` the whole-request latency (admission wait included).
    Single definition site for the serve counters the ISSUE-12 soak and
    `top`'s serve panel read."""
    if not _ENABLED:
        return
    _REGISTRY.counter(
        "abpoa_serve_requests_total",
        "Serve requests by terminal status").inc(1, status=status)
    _REGISTRY.histogram(
        "abpoa_serve_request_seconds",
        "End-to-end request latency (log-bucket sketch, "
        f"~{int(LogSketch.RELATIVE_ERROR * 100)}% quantile tolerance)"
    ).observe(wall_s)


def publish_serve_admitted() -> None:
    if _ENABLED:
        _REGISTRY.counter("abpoa_serve_admitted_total",
                          "Requests admitted into the serve queue").inc(1)


def publish_serve_state(queue_depth: int, inflight: int) -> None:
    """Live queue-depth / in-flight gauges (published on every admission
    and completion event — both are O(1) dict writes)."""
    if not _ENABLED:
        return
    _REGISTRY.gauge("abpoa_serve_queue_depth",
                    "Requests waiting in the serve admission queue").set(
        queue_depth)
    _REGISTRY.gauge("abpoa_serve_inflight",
                    "Requests currently executing in serve workers").set(
        inflight)


# ------------------------------------------------------------- pool hooks

def publish_pool_workers(up: int) -> None:
    """Live (ready) pool worker processes — the supervisor republishes on
    every spawn, death and hard kill."""
    if _ENABLED:
        _REGISTRY.gauge(
            "abpoa_pool_workers",
            "Live process-pool worker processes").set(up)


def materialize_pool_families() -> None:
    """Create the pool metric families at pool start so a run that never
    kills or restarts a worker still exports them at 0 — the chaos/CI
    assertions (and any alerting rule) must be able to read 'zero kills'
    rather than 'family absent'."""
    if not _ENABLED:
        return
    publish_pool_workers(0)
    for key in ("pool.restarts", "pool.kills", "pool.requeues",
                "pool.poison_jobs", "pool.worker_crashes",
                "pool.worker_xla_compiles", "pool.worker_cache_loads",
                "pool.flight_dumps"):
        _REGISTRY.counter(*_EXACT_FAMILIES[key]).inc(0)


def clear_batch_progress() -> None:
    """Zero the batch gauges at run start so a later non-batch run does
    not keep exporting the previous batch's progress. Only touches
    families a batch run already materialized — a process that never ran
    a batch never exports them at all."""
    for name in ("abpoa_batch_sets", "abpoa_batch_sets_done"):
        fam = _REGISTRY.get(name)
        if fam is not None:
            fam.set(0)


# --------------------------------------------------------------------------- #
# textfile exporter (atomic) + background flusher                             #
# --------------------------------------------------------------------------- #

def default_textfile_path() -> str:
    """Where `--metrics` (no argument) writes and `abpoa-tpu top` (no
    argument) reads: one well-known handoff point per user."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    return os.path.join(base, "abpoa_tpu", "metrics.prom")


def write_textfile(path: str) -> None:
    """One atomic exposition write (tmp + rename): a scraper or the `top`
    dashboard never reads a torn file."""
    text = _REGISTRY.render()
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fp:
        fp.write(text)
    os.replace(tmp, path)


class _Flusher(threading.Thread):
    def __init__(self, path: str, interval_s: float) -> None:
        super().__init__(daemon=True, name="abpoa-metrics-flusher")
        self.path = path
        self.interval_s = interval_s
        # NOT `_stop`: Thread.join() calls a private `_stop()` internally
        self._stop_event = threading.Event()

    def run(self) -> None:
        while not self._stop_event.wait(self.interval_s):
            try:
                write_textfile(self.path)
            except Exception:
                # a transient render/IO failure must not kill the
                # exporter for the rest of the run — the next interval
                # writes a fresh frame
                pass

    def stop(self) -> None:
        self._stop_event.set()


_FLUSHER: Optional[_Flusher] = None


def start_textfile_exporter(path: str, interval_s: float = None) -> None:
    """Periodic atomic exposition writes to `path` (`--metrics FILE`) — the
    live feed `abpoa-tpu top` renders while a run executes. Host-side
    rendering only: the flusher reads counters the hot path already
    maintains, it never touches the device."""
    global _FLUSHER
    stop_textfile_exporter()
    if interval_s is None:
        interval_s = float(os.environ.get("ABPOA_TPU_METRICS_INTERVAL_S",
                                          "1.0"))
    write_textfile(path)  # immediate first frame
    _FLUSHER = _Flusher(path, interval_s)
    _FLUSHER.start()


def stop_textfile_exporter(final_write: bool = True) -> None:
    global _FLUSHER
    if _FLUSHER is not None:
        _FLUSHER.stop()
        # join before the final write: both threads use the same tmp
        # path, so an in-flight flusher write racing the final one could
        # rename a torn frame into place
        _FLUSHER.join(timeout=10.0)
        if final_write:
            try:
                write_textfile(_FLUSHER.path)
            except OSError:
                pass
        _FLUSHER = None


# --------------------------------------------------------------------------- #
# stdlib HTTP endpoint                                                        #
# --------------------------------------------------------------------------- #

def start_http_exporter(port: int, host: str = "127.0.0.1"):
    """`/metrics` over stdlib http.server in a daemon thread
    (`--metrics-port N`) — the scrape endpoint the future serve mode
    exposes. Returns the server (call .shutdown() to stop)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_response(404)
                self.end_headers()
                return
            body = _REGISTRY.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # scrape spam stays off stderr
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="abpoa-metrics-http").start()
    return srv


# --------------------------------------------------------------------------- #
# exposition parsing + linting (top dashboard, tests, CI smoke)               #
# --------------------------------------------------------------------------- #

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^}]*\})?'
    r'\s+(?P<value>[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|Inf|NaN))\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str):
    """-> (samples, types): samples maps (name, labels-frozenset) -> float,
    types maps family name -> declared TYPE. The reader `abpoa-tpu top`
    and the lint below share."""
    samples: Dict[Tuple[str, frozenset], float] = {}
    types: Dict[str, str] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {ln}: unparseable sample: {line!r}")
        labels = frozenset(_LABEL_RE.findall(m.group("labels") or ""))
        samples[(m.group("name"), labels)] = float(m.group("value"))
    return samples, types


def sample_value(samples, name: str, **labels) -> Optional[float]:
    return samples.get((name, frozenset((k, str(v))
                                        for k, v in labels.items())))


def sketch_from_exposition(samples, base: str) -> LogSketch:
    """Reconstruct a `LogSketch` from a rendered histogram family.

    Every abpoa histogram is a LogSketch on the SAME fixed bucket grid, so
    each `le` in the exposition maps back to its exact bucket index
    (round-trip through the `{ub:.9g}` render is exact at 5% bucket
    spacing) and counts reconstruct losslessly. Only the exact observed
    min/max are not in the exposition — they degrade to the edge buckets'
    bounds, which moves quantile answers by at most one half-bucket and
    keeps the declared RELATIVE_ERROR contract (tested).
    """
    sk = LogSketch()
    buckets = []
    for (n, lb), v in samples.items():
        if n == base + "_bucket":
            le = dict(lb).get("le")
            if le and le != "+Inf":
                buckets.append((float(le), v))
    if not buckets:
        return sk
    buckets.sort()
    prev = 0.0
    for le, cum in buckets:
        c = int(round(cum - prev))
        prev = cum
        i = int(round((math.log(le) - LogSketch._LOG_LO)
                      / LogSketch._LOG_G)) - 1
        i = max(0, min(LogSketch.N_BUCKETS - 1, i))
        sk.counts[i] += c
    sk.count = int(round(prev))
    s = samples.get((base + "_sum", frozenset()))
    sk.sum = float(s) if s is not None else 0.0
    nz = [i for i, c in enumerate(sk.counts) if c]
    sk.min = LogSketch.LO * LogSketch.GROWTH ** nz[0]
    sk.max = LogSketch.LO * LogSketch.GROWTH ** (nz[-1] + 1)
    return sk


def merge_expositions(texts: List[str]) -> str:
    """Merge N Prometheus expositions into one fleet-wide rollup.

    Counters and gauges sum per (family, label set) — for the families
    this process exports, sums are the fleet-meaningful rollup (total
    requests, total queue depth, breakers open). Histograms merge at the
    LogSketch bucket level (`sketch_from_exposition` + bucket-wise add),
    so merged quantiles carry the same declared tolerance as any single
    sketch. Quantile *gauges* over a merged histogram are recomputed from
    the merged sketch rather than summed — a sum of p99s is meaningless.

    The fleet router's `/metrics` rollup and the standalone `slo --fleet`
    path both go through here, so the two can never disagree.
    """
    parsed = []
    helps: Dict[str, str] = {}
    types_all: Dict[str, str] = {}
    order: List[str] = []
    for text in texts:
        samples, types = parse_exposition(text)
        parsed.append(samples)
        for line in text.splitlines():
            if line.startswith("# HELP "):
                parts = line.split(None, 3)
                if len(parts) >= 3 and parts[2] not in helps:
                    helps[parts[2]] = parts[3] if len(parts) > 3 else ""
        for fam, t in types.items():
            if fam not in types_all:
                types_all[fam] = t
                order.append(fam)
    hist_bases = {f for f, t in types_all.items() if t == "histogram"}
    sketches: Dict[str, LogSketch] = {}
    for base in hist_bases:
        sk = LogSketch()
        for samples in parsed:
            part = sketch_from_exposition(samples, base)
            if part.count:
                sk.merge(part)
        sketches[base] = sk
    out: List[str] = []
    for fam in order:
        t = types_all[fam]
        if helps.get(fam):
            out.append(f"# HELP {fam} {helps[fam]}")
        out.append(f"# TYPE {fam} {t}")
        if t == "histogram":
            sk = sketches[fam]
            buckets = sk.bucket_upper_bounds()
            total = buckets[-1][1] if buckets else 0
            for ub, acc in buckets:
                out.append(f'{fam}_bucket{{le="{ub:.9g}"}} {acc}')
            out.append(f'{fam}_bucket{{le="+Inf"}} {total}')
            out.append(f"{fam}_sum {_num(sk.sum)}")
            out.append(f"{fam}_count {total}")
            continue
        base = fam[:-len("_quantile")] if fam.endswith("_quantile") else None
        if t == "gauge" and base in hist_bases:
            sk = sketches[base]
            qlabels = sorted({dict(lb).get("quantile")
                              for samples in parsed
                              for (n, lb) in samples if n == fam})
            for ql in qlabels:
                if ql is None or not sk.count:
                    continue
                out.append(f'{fam}{{quantile="{ql}"}} '
                           f'{_num(round(sk.quantile(float(ql)), 9))}')
            continue
        acc: Dict[frozenset, float] = {}
        for samples in parsed:
            for (n, lb), v in samples.items():
                if n == fam:
                    acc[lb] = acc.get(lb, 0.0) + v
        for lb in sorted(acc, key=lambda s: sorted(s)):
            out.append(f"{fam}{_fmt_labels(tuple(sorted(lb)))} "
                       f"{_num(acc[lb])}")
    return "\n".join(out) + "\n"


def lint_exposition(text: str) -> List[str]:
    """Structural lint of a Prometheus text exposition: every sample's
    family has a TYPE, counters end in _total, histograms carry a +Inf
    bucket with consistent _count, gauges/counters parse as numbers.
    Returns problems (empty = clean). CI's metrics-smoke gate."""
    problems: List[str] = []
    try:
        samples, types = parse_exposition(text)
    except ValueError as e:
        return [str(e)]
    hist_bases = {n for n, t in types.items() if t == "histogram"}
    for (name, labels), _v in samples.items():
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in hist_bases:
                base = name[:-len(suffix)]
        if base not in types:
            problems.append(f"{name}: sample without a # TYPE declaration")
        elif types[base] == "counter" and not name.endswith("_total"):
            problems.append(f"{name}: counter family without _total suffix")
    for base in hist_bases:
        inf = sample_value(samples, base + "_bucket", le="+Inf")
        cnt = samples.get((base + "_count", frozenset()))
        if inf is None:
            problems.append(f"{base}: histogram without a +Inf bucket")
        elif cnt is not None and inf != cnt:
            problems.append(f"{base}: +Inf bucket {inf} != _count {cnt}")
        buckets = sorted(
            (float(dict(lb)["le"]), v)
            for (n, lb), v in samples.items()
            if n == base + "_bucket" and dict(lb).get("le", "+Inf") != "+Inf")
        last = 0.0
        for ub, v in buckets:
            if v < last:
                problems.append(f"{base}: non-cumulative bucket le={ub}")
            last = v
    return problems
