"""Compile observability: every XLA compile becomes a span + a record.

ROADMAP item 2 (AOT bucket ladder) needs to know WHICH (function, shape
bucket, statics) signatures compile, how long each compile takes, and
whether the persistent compilation cache served it — end-of-run counters
like `fused.recompiles` cannot answer any of that. `compile_watch(...)`
brackets the jitted entry points (the fused chunk, the window batch) and
emits, per dispatch:

- a structured record {fn, bucket, cache_hit, wall_s, xla_compile_s,
  persistent_cache_hit} appended to the run's compile log (rendered as
  the report's `compiles` key, bounded at RECORDS_CAP);
- a `compile:<fn>` trace span when a compile actually happened, so the
  timeline shows the stall where it occurred;
- `compile.misses` / `compile.hits` counters.

Compile detection is ground truth, not a heuristic: the jit wrapper's
in-process executable cache (`fn._cache_size()`) grows exactly when XLA
compiled (or loaded from the persistent cache) for a new signature.
Hosts without `_cache_size` fall back to first-sight-of-key tracking,
which matches jit semantics because the watched bucket IS the signature.
XLA's own compile seconds and the persistent-cache hit/miss verdict come
from `jax.monitoring` listeners ('/jax/backend_compile',
'/jax/compilation_cache/cache_hits|misses'), registered lazily and only
once — a jax-free (numpy/native) run never imports jax through here.

Everything is host-side bookkeeping around dispatches the caller already
makes; nothing adds device syncs.
"""
from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

RECORDS_CAP = 512  # per-run record bound; counters keep exact totals

# run-scoped (reset by report.start_run via reset_run)
_RECORDS: list = []
_DROPPED = 0

# process-global (jit caches are process-global, so hit/miss must be too)
_SEEN_KEYS: dict = {}

# jax.monitoring accumulators (process-global, monotonic)
_MON = {"backend_compile_s": 0.0, "backend_compiles": 0,
        "pcache_hits": 0, "pcache_misses": 0, "registered": False}


def reset_run() -> None:
    global _RECORDS, _DROPPED
    _RECORDS = []
    _DROPPED = 0


def run_records() -> list:
    """This run's compile-log records (the report's `compiles` key)."""
    return list(_RECORDS)


def run_dropped() -> int:
    return _DROPPED


def _register_listeners() -> None:
    """Idempotent jax.monitoring hookup; safe on hosts where the API or
    the events are absent (everything degrades to wall-only records)."""
    if _MON["registered"]:
        return
    _MON["registered"] = True
    try:
        from jax import monitoring
    except Exception:
        return

    def on_duration(event: str, duration: float, **kw) -> None:
        if "backend_compile" in event:
            _MON["backend_compile_s"] += duration
            _MON["backend_compiles"] += 1

    def on_event(event: str, **kw) -> None:
        if event.endswith("compilation_cache/cache_hits"):
            _MON["pcache_hits"] += 1
        elif event.endswith("compilation_cache/cache_misses"):
            _MON["pcache_misses"] += 1

    try:
        monitoring.register_event_duration_secs_listener(on_duration)
        monitoring.register_event_listener(on_event)
    except Exception:
        pass


def _cache_size(jfn) -> Optional[int]:
    try:
        return int(jfn._cache_size())
    except Exception:
        return None


@contextlib.contextmanager
def compile_watch(name: str, jfn, bucket: dict) -> Iterator[dict]:
    """Bracket one dispatch of a jitted entry point.

    `bucket` must carry the signature-determining values (shape buckets +
    static args): it is both the record's attribution payload and the
    fallback compile-detection key. Yields a dict whose `compiled` field
    is valid after exit — drivers use it to count true recompiles.

    The caller must place its host sync (the first `int(...)`/`asarray`
    readback) INSIDE the bracket, so `wall_s` covers compile + execution
    rather than async dispatch alone.
    """
    from .report import count, report
    out = {"compiled": False}
    if not report().enabled:
        yield out
        return
    _register_listeners()
    key = (name, tuple(sorted((k, str(v)) for k, v in bucket.items())))
    before = _cache_size(jfn) if jfn is not None else None
    mon0 = (_MON["pcache_hits"], _MON["pcache_misses"],
            _MON["backend_compile_s"])
    t0 = time.perf_counter()
    # a dispatch that raises (device OOM, fallback path) leaves no record
    # and no _SEEN_KEYS entry — a later successful dispatch of the same
    # bucket must still be detectable as a compile
    yield out
    dt = time.perf_counter() - t0
    after = _cache_size(jfn) if jfn is not None else None
    if before is not None and after is not None:
        compiled = after > before
    else:
        compiled = key not in _SEEN_KEYS
    _SEEN_KEYS[key] = _SEEN_KEYS.get(key, 0) + 1
    out["compiled"] = compiled
    rec = {"fn": name, "bucket": dict(bucket),
           "cache_hit": not compiled, "wall_s": round(dt, 6)}
    if compiled:
        hits_d = _MON["pcache_hits"] - mon0[0]
        miss_d = _MON["pcache_misses"] - mon0[1]
        xla_s = _MON["backend_compile_s"] - mon0[2]
        # a persistent-cache MISS anywhere in the bracket wins: nested
        # helper jits (jnp.zeros -> broadcast_in_dim) can HIT the cache
        # inside a bracket whose own entry point compiled from scratch,
        # and a 2-minute compile must not be labeled a cache load. None
        # when neither event fired (cache disabled, old jax): absence of
        # evidence stays distinguishable from miss
        rec["persistent_cache_hit"] = (False if miss_d > 0 else
                                       (True if hits_d > 0 else None))
        rec["xla_compile_s"] = round(xla_s, 6) if xla_s > 0 else None
        count("compile.misses")
        if xla_s > 0:
            from . import metrics
            if metrics.enabled():
                metrics.registry().counter(
                    "abpoa_xla_compile_seconds_total",
                    "Wall seconds spent inside XLA backend_compile").inc(
                    round(xla_s, 6))
        from . import trace
        trace.add_span("compile:" + name, "compile", t0, dt,
                       args=dict(bucket))
    else:
        count("compile.hits")
    global _DROPPED
    if len(_RECORDS) < RECORDS_CAP:
        _RECORDS.append(rec)
    else:
        _DROPPED += 1
