"""Cell-updates FLOPs/bytes model -> MFU estimate.

The POA DP kernel is integer vector work; there is no hardware counter for
"POA cells/s", so the model counts the arithmetic the recurrence performs
per in-band cell and divides by the device's published peak. Assumptions
(documented in PERF.md):

- Ops per cell by gap regime (adds + max ops in the H/E/F recurrences,
  including the band masking select): linear 8, affine 16, convex 26.
  These match a hand count of _dp_banded's per-cell arithmetic; the
  reference SIMD kernel does the same work per cell
  (abpoa_align_simd.c:935-1074).
- Peak ops/s uses the chip's published dense-matmul peak as the capability
  proxy (the VPU's int path has no separately published number). MFU here
  is therefore a LOWER-bound-flavored utilization estimate, comparable
  across runs on the same chip generation — its job is trend attribution,
  not an absolute roofline claim.
- Cell totals are host-side models of work dispatched (graph rows x band
  window), not device readbacks; the fused loop's total is an estimate
  from its static buckets (see fused_loop.py).
"""
from __future__ import annotations

from typing import Optional

from .. import constants as C

# integer ops per in-band DP cell (model, see module docstring)
CELL_INT_OPS = {
    C.LINEAR_GAP: 8,
    C.AFFINE_GAP: 16,
    C.CONVEX_GAP: 26,
}

# published dense peak ops/s per chip generation (substring-matched against
# jax's device_kind, lowercase). bf16 MXU numbers — see module docstring.
# libtpu spells the lite chips two ways across releases ("TPU v5 lite" /
# "TPU v5e"); both spellings must hit, and the lite keys must be checked
# before the bare-generation fallbacks.
_PEAK_OPS = (
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("v5 lite", 394e12),
    ("v5e", 394e12),
    ("v5litepod", 394e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_ops_for_kind(kind: str) -> Optional[float]:
    k = (kind or "").lower()
    for key, peak in _PEAK_OPS:
        if key in k:
            return peak
    return None


# phases whose wall time covers the DP dispatches the cell counters model
_ALIGN_PHASES = ("align", "align_fused")


def mfu_block(rep, device: Optional[dict]) -> Optional[dict]:
    """The report's `mfu` section. Cell-updates/s is emitted on every
    backend (the cross-paper throughput metric); the MFU ratio itself only
    when a non-CPU device with a known peak ran the work."""
    cells = rep.counters.get("dp.cells", 0)
    if not cells:
        return None
    ops = rep.counters.get("dp.cell_ops", 0)
    align_wall = sum(rep.phases[p][0] for p in _ALIGN_PHASES
                     if p in rep.phases)
    block = {
        "dp_cells": cells,
        "dp_cell_ops": ops,
        "align_wall_s": round(align_wall, 6),
        "cell_updates_per_sec": (round(cells / align_wall, 1)
                                 if align_wall > 0 else None),
        "model_ops_per_sec": (round(ops / align_wall, 1)
                              if align_wall > 0 else None),
        "peak_ops_per_sec": None,
        "mfu": None,
    }
    if device and device.get("platform") not in (None, "cpu"):
        peak = peak_ops_for_kind(device.get("kind", ""))
        if peak and align_wall > 0:
            block["peak_ops_per_sec"] = peak
            # significant digits, not decimal places: real MFUs here can
            # be far below 1e-8 and must not round to zero
            block["mfu"] = float(f"{ops / align_wall / peak:.6g}")
    return block
