"""Per-round timeline: the bounded ring behind the shard-skew row.

The lockstep/map/sharded drivers all run the same shape — one fused DP
dispatch per round over a lane table — and the aggregate counters
(`lockstep.chunks`, occupancy EWMAs) already say *how many* rounds ran.
What they cannot answer is the question the first on-chip soak will ask
within minutes: "which mesh shard was the straggler in round 12, and
how skewed was that round?" — the per-stage/per-shard attribution SeGraM
reports (arXiv:2205.05883). This module records it: every round lands
one bounded-ring sample carrying the round wall, the DISPATCH wall (the
fused device bracket alone, measured around the same code the
`dp_chunk` trace span brackets, so the round timeline reconciles with
`span_totals("dp")` by construction), live-lane count, K cap, and —
when the round ran sharded — the per-shard live-lane split.

Per-shard *walls* are estimates, and say so: a sharded round is ONE
fused `shard_map` dispatch, so the host can only time the straggler
(the fused wall IS the max shard wall). Each shard's wall is attributed
proportionally to its live lanes; the max-live shard is the straggler
whose estimate equals the measured dispatch wall exactly. The skew
ratio (max/min over live shards) is exact in *lanes* even though the
walls are modeled.

Surfaces: `/metrics` (`abpoa_round_wall_seconds` histogram +
`abpoa_shard_skew_ratio` / `abpoa_shard_round_wall_seconds{shard=}` /
`abpoa_shard_straggler` gauges), extra Chrome trace tracks (tid 900 =
rounds, 910+i = shard estimates) when tracing is armed, the `top`
shard-skew row, and the `why` "slowest shard" line (serve attaches
`skew_summary()` to sharded request records).

Overhead contract mirrors trace.py: one lock acquire and one tuple
store per round (rounds are ~10-100 ms each, so the hook is noise);
the ring is bounded (``ABPOA_TPU_ROUNDS_CAP``, default 4096) and
overwrites oldest, reporting `dropped()` instead of growing.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

DEFAULT_CAPACITY = 4096

# reserved Chrome-trace track ids: the round timeline and per-shard
# estimate tracks must not collide with live thread tids (dense from 1)
# or foreign worker pids
ROUNDS_TID = 900
SHARD_TID_BASE = 910


class RoundSample(NamedTuple):
    route: str                  # "lockstep" | "sharded" | "map"
    t_start: float              # perf_counter at round start
    wall_s: float               # full round wall (host fusion included)
    dp_wall_s: float            # fused dispatch bracket(s) only
    lanes: int                  # live lanes this round
    k_cap: int                  # group capacity (global lanes if sharded)
    mesh: int                   # mesh size (1 = unsharded)
    shard_live: Optional[Tuple[int, ...]]  # per-shard live lanes


# per-thread accumulation between begin_round() and record_round(): the
# dispatch sites (align/dp_chunk.py, parallel/shard.py) note their walls
# here without knowing which driver's round they serve; thread-local
# because serve runs concurrent lockstep groups on worker threads
_TLS = threading.local()


def rounds_enabled() -> bool:
    """ABPOA_TPU_ROUNDS=0 disables round recording — the operator
    kill-switch, and the paired-server overhead check's OFF side."""
    return os.environ.get("ABPOA_TPU_ROUNDS", "1") not in ("0", "off")


def _capacity() -> int:
    try:
        return max(16, int(os.environ.get("ABPOA_TPU_ROUNDS_CAP",
                                          str(DEFAULT_CAPACITY))))
    except ValueError:
        return DEFAULT_CAPACITY


class RoundRing:
    """Bounded ring of RoundSamples (trace.Tracer's ring discipline)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = capacity or _capacity()
        self._lock = threading.Lock()
        self._buf: List[RoundSample] = []
        self._n = 0

    def add(self, s: RoundSample) -> None:
        with self._lock:
            if self._n < self.capacity:
                self._buf.append(s)
            else:
                self._buf[self._n % self.capacity] = s
            self._n += 1

    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    @property
    def total(self) -> int:
        return self._n

    def samples(self) -> List[RoundSample]:
        with self._lock:
            if self._n <= self.capacity:
                return list(self._buf)
            k = self._n % self.capacity
            return self._buf[k:] + self._buf[:k]


_RING = RoundRing()


def ring() -> RoundRing:
    return _RING


def reset(capacity: Optional[int] = None) -> None:
    global _RING
    _RING = RoundRing(capacity)
    _TLS.dp_wall = 0.0
    _TLS.shard_live = None


def dropped() -> int:
    return _RING.dropped


# ------------------------------------------------------------- recording

def begin_round() -> None:
    """Zero this thread's dispatch accumulation — called by the drivers
    where they stamp the round start, so a warmer's stray dispatch on
    the same thread can never leak into the next round's dp wall."""
    _TLS.dp_wall = 0.0
    _TLS.shard_live = None


def note_dispatch(wall_s: float,
                  shard_live: Optional[Sequence[int]] = None) -> None:
    """One fused dispatch bracket completed on this thread: accumulate
    its wall (W-growth retries and amb-strand re-dispatches sum) and,
    for sharded rounds, keep the per-shard live-lane split."""
    _TLS.dp_wall = getattr(_TLS, "dp_wall", 0.0) + float(wall_s)
    if shard_live is not None:
        _TLS.shard_live = tuple(int(x) for x in shard_live)


def record_round(route: str, lanes: int, k_cap: int, wall_s: float,
                 mesh: int = 1) -> RoundSample:
    """Seal one round into the ring and fan it out to /metrics and the
    trace. Called by the drivers at the point they already compute the
    round's amortized share, so the hook adds no new clock reads to the
    round loop beyond the dispatch bracket."""
    dp_wall = getattr(_TLS, "dp_wall", 0.0)
    shard_live = getattr(_TLS, "shard_live", None)
    begin_round()
    if not rounds_enabled():
        return RoundSample(route=route, t_start=0.0, wall_s=float(wall_s),
                           dp_wall_s=dp_wall, lanes=int(lanes),
                           k_cap=int(k_cap), mesh=int(mesh),
                           shard_live=shard_live)
    s = RoundSample(route=route, t_start=time.perf_counter() - wall_s,
                    wall_s=float(wall_s), dp_wall_s=dp_wall,
                    lanes=int(lanes), k_cap=int(k_cap), mesh=int(mesh),
                    shard_live=shard_live)
    _RING.add(s)
    from . import metrics, trace
    metrics.publish_round(route, s.wall_s, s.lanes, s.k_cap)
    if s.shard_live and s.mesh > 1:
        walls = shard_wall_estimates(s)
        ratio, straggler = skew_of(s)
        metrics.publish_shard_skew(ratio, straggler, walls)
    if trace.enabled():
        _trace_round(s)
    return s


def shard_wall_estimates(s: RoundSample) -> Dict[int, float]:
    """Per-shard wall estimates for one sharded round: the dispatch wall
    attributed proportionally to live lanes (the fused dispatch is the
    max-live shard's wall; emptier shards idle behind it)."""
    live = s.shard_live or ()
    peak = max(live) if live else 0
    if peak <= 0:
        return {i: 0.0 for i in range(len(live))}
    return {i: s.dp_wall_s * n / peak for i, n in enumerate(live)}


def skew_of(s: RoundSample) -> Tuple[float, int]:
    """(skew ratio, straggler shard id) of one sharded round: max/min
    live lanes over shards that had any (empty shards are excluded — a
    drained trailing shard would make every ratio infinite); straggler =
    the max-live shard, whose estimated wall is the measured one."""
    live = s.shard_live or ()
    if not live:
        return 1.0, 0
    peak = max(live)
    straggler = live.index(peak)
    floor = min((n for n in live if n > 0), default=peak)
    return (peak / floor if floor else 1.0), straggler


def _trace_round(s: RoundSample) -> None:
    from . import trace
    t = trace.tracer()
    args = {"route": s.route, "lanes": s.lanes, "k_cap": s.k_cap,
            "dp_wall_ms": round(s.dp_wall_s * 1e3, 3)}
    if s.mesh > 1:
        args["mesh"] = s.mesh
    t.add_foreign("X", f"round[{s.route}]", "round", s.t_start, s.wall_s,
                  ROUNDS_TID, args, None)
    if s.shard_live and s.mesh > 1:
        for i, w in shard_wall_estimates(s).items():
            t.add_foreign("X", f"shard{i}", "round", s.t_start, w,
                          SHARD_TID_BASE + i,
                          {"live": s.shard_live[i], "est": True}, None)


# --------------------------------------------------------------- reading

def snapshot(n: int = 0) -> List[dict]:
    """Newest `n` round samples (0 = all retained), oldest-first, as
    plain dicts — the `why`/test-facing view."""
    out = [s._asdict() for s in _RING.samples()]
    return out[-n:] if n else out


def dp_wall_total(route: Optional[str] = None) -> float:
    """Sum of recorded dispatch walls — the reconcile test pins this
    within 5% of `trace.span_totals("dp")`'s `dp_chunk` sum."""
    return sum(s.dp_wall_s for s in _RING.samples()
               if route is None or s.route == route)


def skew_summary() -> Optional[dict]:
    """The newest sharded round's skew verdict, None when no sharded
    round ran: what serve attaches to a sharded request's archive record
    and `why` renders as the "slowest shard" line."""
    for s in reversed(_RING.samples()):
        if s.mesh > 1 and s.shard_live:
            ratio, straggler = skew_of(s)
            walls = shard_wall_estimates(s)
            return {
                "slowest_shard": straggler,
                "shard_skew": round(ratio, 3),
                "round_wall_ms": round(s.wall_s * 1e3, 3),
                "shard_wall_ms": {str(i): round(w * 1e3, 3)
                                  for i, w in walls.items()},
                "shard_live": list(s.shard_live),
            }
    return None
