"""Byte-golden regression tests against the reference's shipped outputs.

Mirrors the reference test strategy (/root/reference/tests/run_all.sh:30-50):
exact-byte determinism of consensus / majority-vote / diploid outputs.
"""
import io
import os
import subprocess
import sys

import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def run_cli(args):
    out = io.StringIO()
    from abpoa_tpu.cli import build_parser, args_to_params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    ns = build_parser().parse_args(args)
    abpt = args_to_params(ns).finalize()
    ab = Abpoa()
    msa_from_file(ab, abpt, ns.input, out)
    return out.getvalue()


def golden(name):
    with open(os.path.join(GOLDEN_DIR, name)) as fp:
        return fp.read()


def test_consensus_golden():
    assert run_cli([os.path.join(DATA_DIR, "seq.fa")]) == golden("ref_consensus.txt")


def test_majority_vote_golden():
    assert run_cli([os.path.join(DATA_DIR, "seq.fa"), "-a1"]) == golden("ref_msa.txt")


def test_heter_2cons_golden():
    assert run_cli([os.path.join(DATA_DIR, "heter.fa"), "-d2"]) == golden("ref_heter.txt")
