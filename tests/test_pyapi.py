"""pyabpoa-API-compat tests: same call surface, consistent with CLI output."""
import os

from conftest import DATA_DIR, GOLDEN_DIR


def _read_seqs(path):
    seqs, cur = [], []
    with open(path) as fp:
        for ln in fp:
            ln = ln.strip()
            if ln.startswith(">"):
                if cur:
                    seqs.append("".join(cur))
                cur = []
            elif ln:
                cur.append(ln)
    if cur:
        seqs.append("".join(cur))
    return seqs


def test_msa_consensus_matches_golden():
    import abpoa_tpu.pyapi as pa
    seqs = _read_seqs(os.path.join(DATA_DIR, "seq.fa"))
    a = pa.msa_aligner()
    res = a.msa(seqs, out_cons=True, out_msa=False)
    with open(os.path.join(GOLDEN_DIR, "ref_consensus.txt")) as fp:
        golden_seq = fp.read().splitlines()[1]
    assert res.n_cons == 1
    assert res.cons_seq[0] == golden_seq
    assert res.cons_len[0] == len(golden_seq)
    assert len(res.cons_cov[0]) == len(golden_seq)
    assert len(res.cons_qv[0]) == len(golden_seq)


def test_msa_rows():
    import abpoa_tpu.pyapi as pa
    seqs = _read_seqs(os.path.join(DATA_DIR, "seq.fa"))
    a = pa.msa_aligner()
    res = a.msa(seqs, out_cons=True, out_msa=True)
    assert res.msa_len > 0
    assert len(res.msa_seq) == len(seqs) + res.n_cons
    for row in res.msa_seq:
        assert len(row) == res.msa_len


def test_incremental_add():
    import abpoa_tpu.pyapi as pa
    seqs = _read_seqs(os.path.join(DATA_DIR, "seq.fa"))
    a = pa.msa_aligner()
    a.msa_align(seqs[:5], out_cons=True, out_msa=False)
    a.msa_add(seqs[5:])
    res = a.msa_output()
    b = pa.msa_aligner()
    res_all = b.msa(seqs, out_cons=True, out_msa=False)
    assert res.cons_seq == res_all.cons_seq


def test_two_cons_diploid():
    import abpoa_tpu.pyapi as pa
    seqs = _read_seqs(os.path.join(DATA_DIR, "heter.fa"))
    a = pa.msa_aligner()
    res = a.msa(seqs, out_cons=True, out_msa=False, max_n_cons=2)
    with open(os.path.join(GOLDEN_DIR, "ref_heter.txt")) as fp:
        lines = fp.read().splitlines()
    assert res.n_cons == 2
    assert res.cons_seq[0] == lines[1]
    assert res.cons_seq[1] == lines[3]


def test_msa_batch_lockstep_parity():
    """msa_batch runs K sets through the lockstep fused loop; results match
    per-set sequential msa() on the numpy engine."""
    import numpy as np
    import abpoa_tpu.pyapi as pa

    def mkset(seed, n=4, L=120):
        r = np.random.default_rng(seed)
        ref = r.integers(0, 4, L)
        return ["".join("ACGT"[(b + r.integers(1, 4)) % 4]
                        if r.random() < 0.1 else "ACGT"[b] for b in ref)
                for _ in range(n)]

    # different length buckets: msa_batch partitions into same-bucket
    # sub-batches; results must still come back in input order
    sets = [mkset(0), mkset(1, L=400), mkset(2)]
    # lockstep="on": CPU-only hosts default to the serial K=1 path
    # (round 8 measurement); this test exercises the vmapped path itself
    dev = pa.msa_aligner(device="jax", lockstep="on")
    batch = dev.msa_batch(sets, out_cons=True, out_msa=True)
    for k, ss in enumerate(sets):
        host = pa.msa_aligner(device="numpy")
        want = host.msa(ss, out_cons=True, out_msa=True)
        assert batch[k].cons_seq == want.cons_seq, f"set {k}"
        assert batch[k].msa_seq == want.msa_seq, f"set {k}"
