"""Unit tests for the seeding layer internals."""
import numpy as np

from abpoa_tpu.params import Params
from abpoa_tpu.seed import (collect_mm, dp_chaining, lis_chaining, mm_sketch)


def test_mm_sketch_positions_sorted_and_within_range():
    rng = np.random.default_rng(0)
    seq = rng.integers(0, 4, 500).astype(np.uint8)
    out = []
    mm_sketch(seq, 10, 19, 7, False, out)
    assert out, "sketch should emit minimizers"
    for x, y in out:
        rid = y >> 32
        pos = (y & 0xFFFFFFFF) >> 1
        assert rid == 7
        assert 18 <= pos < 500


def test_lis_chaining_monotone_spacing():
    # anchors strand<<63 | tpos<<32 | qpos with a noisy diagonal
    rng = np.random.default_rng(1)
    anchors = []
    for t in range(0, 3000, 37):
        q = t + int(rng.integers(-5, 6))
        if q < 0:
            continue
        anchors.append((t << 32) | q)
    anchors.append((1 << 63) | (10 << 32) | 20)  # stray rc anchor
    anchors.sort()
    chain = lis_chaining(anchors, min_w=100)
    assert chain
    last_t = last_q = -1
    for a in chain:
        t = (a >> 32) & 0x7FFFFFFF
        q = a & 0xFFFFFFFF
        assert t - last_t >= 100 and q - last_q >= 100
        assert not (a >> 63)
        last_t, last_q = t, q


def test_dp_chaining_produces_spaced_anchors():
    abpt = Params()
    abpt.min_w = 100
    abpt.finalize()
    anchors = []
    for t in range(0, 4000, 41):
        anchors.append((t << 32) | t)
    par = []
    dp_chaining(anchors, abpt, 4000, 4000, par)
    assert par
    last_t = -10**9
    for a in par:
        t = (a >> 32) & 0x7FFFFFFF
        assert t - last_t >= abpt.min_w + abpt.k
        last_t = t
