"""Feature-path goldens: amino acids, score-matrix files, incremental MSA
(GFA + MSA restore), file-list batch mode, plot dot output."""
import io
import os

import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def run_cli(args):
    out = io.StringIO()
    from abpoa_tpu.cli import build_parser, args_to_params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    ns = build_parser().parse_args(args)
    abpt = args_to_params(ns).finalize()
    ab = Abpoa()
    if ns.in_list:
        with open(ns.input) as lf:
            bi = 1
            for line in lf:
                fn = line.strip()
                if fn:
                    abpt.batch_index = bi
                    msa_from_file(ab, abpt, fn, out)
                    bi += 1
    else:
        msa_from_file(ab, abpt, ns.input, out)
    return out.getvalue()


def golden(name):
    with open(os.path.join(GOLDEN_DIR, name)) as fp:
        return fp.read()


def test_amino_acid():
    got = run_cli([os.path.join(DATA_DIR, "aa.fa"), "-c"])
    assert got == golden("aa_cons.txt")


def test_blosum62():
    got = run_cli([os.path.join(DATA_DIR, "aa.fa"), "-c",
                   "-t", os.path.join(DATA_DIR, "BLOSUM62.mtx")])
    assert got == golden("aa_blosum62.txt")


def test_incremental_native_engine():
    """Incremental MSA (-i) through the native graph engine (GFA and MSA
    restore) must byte-match the pure-Python engine (VERDICT round-1
    weak item: native was silently excluded for -i)."""
    for restore in ("seq10.gfa", "seq10.msa"):
        args = [os.path.join(DATA_DIR, "seq4.fa"),
                "-i", os.path.join(DATA_DIR, restore)]
        want = run_cli(args + ["--device", "numpy"])
        got = run_cli(args + ["--device", "native"])
        assert got == want, restore


def test_incremental_gfa():
    got = run_cli([os.path.join(DATA_DIR, "seq4.fa"),
                   "-i", os.path.join(DATA_DIR, "seq10.gfa")])
    assert got == golden("incr_gfa.txt")


def test_incremental_msa():
    got = run_cli([os.path.join(DATA_DIR, "seq4.fa"),
                   "-i", os.path.join(DATA_DIR, "seq10.msa")])
    assert got == golden("incr_msa.txt")


def test_list_mode():
    got = run_cli([os.path.join(DATA_DIR, "list.txt"), "-l"])
    assert got == golden("list_mode.txt")


def test_plot_dot(tmp_path):
    out = tmp_path / "g.png"
    run_cli([os.path.join(DATA_DIR, "seq.fa"), "-g", str(out)])
    dot = str(out) + ".dot"
    assert os.path.exists(dot)
    text = open(dot).read()
    assert "digraph ABPOA_graph" in text and "rank=same" in text


def test_rc_mixed_strand_seeded():
    got = run_cli([os.path.join(DATA_DIR, "rcmix.fa"), "-s", "-S", "-n", "200"])
    assert got == golden("rcmix_sS.txt")


def test_rc_mixed_strand_seeded_progressive():
    got = run_cli([os.path.join(DATA_DIR, "rcmix.fa"), "-s", "-S", "-p", "-n", "200"])
    assert got == golden("rcmix_sSp.txt")


def test_v3_dp_matrix_dump():
    """-V3 dumps the banded DP matrix for kernel debugging (the reference's
    __SIMD_DEBUG__ path, src/abpoa_align_simd.c:46-95; SURVEY §5) without
    changing stdout."""
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(DATA_DIR, "test.fa")
    base = subprocess.run(
        [sys.executable, "-m", "abpoa_tpu.cli", "--device", "numpy", path],
        capture_output=True, text=True, timeout=300, cwd=root)
    v3 = subprocess.run(
        [sys.executable, "-m", "abpoa_tpu.cli", "--device", "numpy", "-V3",
         path],
        capture_output=True, text=True, timeout=300, cwd=root)
    assert v3.returncode == 0
    assert v3.stdout == base.stdout
    assert "[abpoa_tpu::dp] row 0" in v3.stderr
    assert "H:" in v3.stderr
    assert "[abpoa_tpu::dp]" not in base.stderr


def test_device_ineligible_reroutes_to_host(capsys):
    """-G (path scores) with --device pallas must run the native host kernel
    (one warning), not per-alignment device dispatches (VERDICT r4 task 6)."""
    import io
    from abpoa_tpu.params import Params
    from abpoa_tpu import pipeline as pl

    pl._REROUTE_WARNED = False
    abpt = Params()
    abpt.device = "pallas"
    abpt.inc_path_score = True
    abpt.finalize()
    out = io.StringIO()
    pl.msa_from_file(pl.Abpoa(), abpt, os.path.join(DATA_DIR, "seq.fa"), out)
    err = capsys.readouterr().err
    assert "outside the fused device loop" in err
    assert abpt.device == "pallas"  # restored after the run

    want = io.StringIO()
    a2 = Params()
    a2.device = "native"
    a2.inc_path_score = True
    a2.finalize()
    pl.msa_from_file(pl.Abpoa(), a2, os.path.join(DATA_DIR, "seq.fa"), want)
    assert out.getvalue() == want.getvalue()
