"""End-to-end all-device progressive POA must reproduce the standard
pipeline's consensus (graph tables built on device, DP + backtrack on device,
fusion + topo on device)."""
import numpy as np

from abpoa_tpu import constants as C
from abpoa_tpu.graph import POAGraph
from abpoa_tpu.params import Params
from abpoa_tpu.pipeline import Abpoa, poa
from abpoa_tpu.cons.consensus import generate_consensus

from test_device_graph import _random_reads


import pytest


@pytest.mark.parametrize("gap", ["convex", "affine"])
def test_device_pipeline_consensus_matches(gap):
    from abpoa_tpu.align.device_pipeline import (progressive_poa_device,
                                                 device_graph_to_python)

    rng = np.random.default_rng(11)
    reads = _random_reads(rng, 6, 140)
    abpt = Params()
    abpt.device = "numpy"
    if gap == "affine":
        abpt.gap_open2 = 0
    abpt.finalize()

    # standard host pipeline
    ab = Abpoa()
    for r in reads:
        ab.names.append("")
        ab.comments.append("")
        ab.quals.append(None)
        ab.seqs.append("x" * len(r))
        ab.is_rc.append(False)
    weights = [np.ones(len(r), dtype=np.int64) for r in reads]
    poa(ab, abpt, reads, weights, 0)
    cons_host = generate_consensus(ab.graph, abpt, len(reads)).cons_base

    # all-device pipeline
    g = progressive_poa_device(reads, abpt)
    pg = device_graph_to_python(g, abpt)
    cons_dev = generate_consensus(pg, abpt, len(reads)).cons_base

    assert cons_host == cons_dev
