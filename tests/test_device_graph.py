"""Device-resident graph fusion/topo must agree with the host graph engine.

This validates the round-2 all-device progressive loop's core: run the same
progressive POA with (a) host-side fusion (graph.py) and (b) jitted
device-side fusion + topo sort (align/device_graph.py), and compare the full
graph structure after every read.
"""
import numpy as np
import pytest

from abpoa_tpu import constants as C
from abpoa_tpu.graph import POAGraph
from abpoa_tpu.params import Params


def _random_reads(rng, n_reads, length, err=0.12):
    ref = rng.integers(0, 4, length)
    reads = []
    for _ in range(n_reads):
        read = []
        for b in ref:
            x = rng.random()
            if x < err * 0.4:
                read.append((b + rng.integers(1, 4)) % 4)
            elif x < err * 0.7:
                read.append(b)
                read.append(rng.integers(0, 4))
            elif x < err:
                pass
            else:
                read.append(b)
        reads.append(np.array(read, dtype=np.uint8))
    return reads


def test_device_fusion_matches_host():
    import jax.numpy as jnp
    from abpoa_tpu.align.device_graph import (DeviceGraph, fuse_alignment,
                                              init_device_graph, topo_sort,
                                              ops_from_cigar)
    from abpoa_tpu.align import align_sequence_to_graph

    rng = np.random.default_rng(3)
    reads = _random_reads(rng, 5, 120)
    abpt = Params().finalize()

    host = POAGraph()
    N, E, A, MAX_OPS = 1024, 8, 4, 512
    dev = init_device_graph(N, E, A)

    for read_id, seq in enumerate(reads):
        w = np.ones(len(seq), dtype=np.int64)
        res_cigar = []
        if host.node_n > 2:
            res = align_sequence_to_graph(host, abpt, seq)
            res_cigar = res.cigar
        # host fusion
        host.add_alignment(abpt, seq, w, None, res_cigar, read_id,
                           len(reads), True)
        # device fusion of the SAME op stream
        ops, n_ops = ops_from_cigar(res_cigar, MAX_OPS)
        qpad = np.zeros(N, dtype=np.int32)
        qpad[: len(seq)] = seq
        wpad = np.ones(N, dtype=np.int32)
        dev = fuse_alignment(dev, jnp.asarray(ops), jnp.int32(n_ops),
                             jnp.asarray(qpad), jnp.int32(len(seq)),
                             jnp.asarray(wpad),
                             C.SRC_NODE_ID, C.SINK_NODE_ID, max_ops=MAX_OPS)
        dev_sorted, i2n, n2i, remain, ok = topo_sort(dev)
        dev = dev_sorted  # carry the sorted edge order, like the host engine
        assert bool(ok), f"device graph overflow at read {read_id}"

        # ---- compare structure -------------------------------------------
        n = host.node_n
        assert int(dev.node_n) == n
        base_d = np.asarray(dev.base)[:n]
        base_h = np.array([nd.base for nd in host.nodes])
        np.testing.assert_array_equal(base_d, base_h)
        out_cnt = np.asarray(dev_sorted.out_cnt)
        out_ids = np.asarray(dev_sorted.out_ids)
        out_w = np.asarray(dev_sorted.out_w)
        in_cnt = np.asarray(dev_sorted.in_cnt)
        for nid in range(n):
            nd = host.nodes[nid]
            assert int(out_cnt[nid]) == len(nd.out_ids), f"node {nid} out_cnt"
            assert int(in_cnt[nid]) == len(nd.in_ids), f"node {nid} in_cnt"
            assert list(out_ids[nid][: len(nd.out_ids)]) == nd.out_ids, \
                f"node {nid} out order"
            assert list(out_w[nid][: len(nd.out_w)]) == nd.out_w
            d_al = sorted(np.asarray(dev_sorted.aligned)[nid][: int(np.asarray(dev_sorted.aligned_cnt)[nid])])
            assert d_al == sorted(nd.aligned_ids), f"node {nid} aligned group"
        # topo order + max_remain
        i2n_h = host.index_to_node_id[:n]
        np.testing.assert_array_equal(np.asarray(i2n)[:n], i2n_h)
        np.testing.assert_array_equal(np.asarray(remain)[:n],
                                      host.node_id_to_max_remain[:n])
