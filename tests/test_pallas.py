"""Pallas banded-kernel parity tests (interpret mode on the CPU mesh)."""
import io
import os

import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def _pallas_importable():
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _pallas_importable(),
                                reason="pallas unavailable in this env")


def run_cli(args):
    out = io.StringIO()
    from abpoa_tpu.cli import build_parser, args_to_params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    ns = build_parser().parse_args(args)
    abpt = args_to_params(ns).finalize()
    ab = Abpoa()
    msa_from_file(ab, abpt, ns.input, out)
    return out.getvalue()


def golden(name):
    with open(os.path.join(GOLDEN_DIR, name)) as fp:
        return fp.read()


def test_pallas_consensus_golden():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "--device", "pallas"])
    assert got == golden("ref_consensus.txt")


def test_pallas_heter_2cons():
    got = run_cli([os.path.join(DATA_DIR, "heter.fa"), "-d2",
                   "--device", "pallas"])
    assert got == golden("ref_heter.txt")
