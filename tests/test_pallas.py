"""Pallas banded-kernel parity tests (interpret mode on the CPU mesh)."""
import io
import os

import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def _pallas_importable():
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _pallas_importable(),
                                reason="pallas unavailable in this env")


def run_cli(args):
    out = io.StringIO()
    from abpoa_tpu.cli import build_parser, args_to_params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    ns = build_parser().parse_args(args)
    abpt = args_to_params(ns).finalize()
    ab = Abpoa()
    msa_from_file(ab, abpt, ns.input, out)
    return out.getvalue()


def golden(name):
    with open(os.path.join(GOLDEN_DIR, name)) as fp:
        return fp.read()


def test_pallas_consensus_golden():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "--device", "pallas"])
    assert got == golden("ref_consensus.txt")


@pytest.mark.slow
def test_pallas_heter_2cons():
    got = run_cli([os.path.join(DATA_DIR, "heter.fa"), "-d2",
                   "--device", "pallas"])
    assert got == golden("ref_heter.txt")


def test_pallas_perread_compiled_on_chip():
    """Compiled parity for the per-read pallas backend (pallas_kernel.py) on
    the real accelerator: the fused loop is stubbed out so the pipeline takes
    the per-read dispatch path. Subprocess-isolated with a timeout."""
    import subprocess
    import sys
    from test_pallas_fused import _accelerator_reachable
    if not _accelerator_reachable():
        pytest.skip("no accelerator reachable (wedged tunnel or CPU-only)")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = """
import io, sys
sys.path.insert(0, {root!r})
import abpoa_tpu.align.fused_loop as fl
fl.fused_eligible = lambda *a, **k: False
from abpoa_tpu.cli import build_parser, args_to_params
from abpoa_tpu.pipeline import Abpoa, msa_from_file
ns = build_parser().parse_args([{path!r}, '--device', 'pallas'])
abpt = args_to_params(ns).finalize()
out = io.StringIO()
msa_from_file(Abpoa(), abpt, ns.input, out)
sys.stdout.write(out.getvalue())
""".format(root=root, path=os.path.join(DATA_DIR, "seq.fa"))
    from test_pallas_fused import _device_env
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=900,
                          env={**_device_env(), "ABPOA_TPU_SKIP_PROBE": "1"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout == golden("ref_consensus.txt")
