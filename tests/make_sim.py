"""Generate a synthetic long-read consensus test set (ONT-like error profile)."""
import argparse
import random


def simulate(ref_len, n_reads, err, seed, out):
    rng = random.Random(seed)
    ref = "".join(rng.choice("ACGT") for _ in range(ref_len))
    sub = err * 0.4
    ins = err * 0.3
    dele = err * 0.3
    with open(out, "w") as fp:
        for r in range(n_reads):
            read = []
            for ch in ref:
                x = rng.random()
                if x < sub:
                    read.append(rng.choice([c for c in "ACGT" if c != ch]))
                elif x < sub + ins:
                    read.append(ch)
                    read.append(rng.choice("ACGT"))
                elif x < sub + ins + dele:
                    pass
                else:
                    read.append(ch)
            fp.write(f">read_{r}\n{''.join(read)}\n")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref-len", type=int, default=10000)
    ap.add_argument("--n-reads", type=int, default=20)
    ap.add_argument("--err", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--out", type=str, required=True)
    simulate(**vars(ap.parse_args()))
