"""PR-15 tests: cross-process request tracing, the worker flight
recorder, and the `abpoa-tpu why` postmortem analyzer.

- request-context tagging + per-request export (unit)
- trace reconciliation across the process boundary: a pool job's
  worker-side span tree, shipped over the pipe and merged, sums to
  within 5% of the parent-observed job wall (PR-7 contract extended to
  the pool path)
- flight-recorder harvest under injected worker_kill / worker_sigsegv:
  the fault record carries the dump path, the dump names the job
- `why` golden-output on a checked-in dump + archive-id lookup
- slo offender ids; loadgen slowest-N summary
- sampling-off overhead guard at the PR-6/7 bound
"""
import io
import json
import os
import time

import pytest

from conftest import DATA_DIR

SIM2K = os.path.join(DATA_DIR, "sim2k.fa")
GOLDEN_DUMP = os.path.join(DATA_DIR, "flight_dump.json")


@pytest.fixture(autouse=True)
def _clean_state():
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    rz.inject.reset()
    obs.trace_disable()
    yield
    rz.inject.reset()
    obs.trace_disable()
    obs.flight.uninstall()
    obs.start_run()


def _pool_params(workers):
    from abpoa_tpu.params import Params
    abpt = Params()
    abpt.device = "numpy"   # jax-import-free workers: ~0.5s spawns
    abpt.workers = workers
    return abpt.finalize()


def _sim_files(tmp_path, n, ref_len=120):
    import subprocess
    import sys
    files = []
    for s in range(n):
        p = str(tmp_path / f"why{s}.fa")
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "make_sim.py"),
             "--ref-len", str(ref_len), "--n-reads", "4", "--err", "0.1",
             "--seed", str(900 + s), "--out", p], check=True)
        files.append(p)
    return files


# --------------------------------------------------------------------- #
# trace-context units                                                    #
# --------------------------------------------------------------------- #

def test_request_ctx_tags_and_filters():
    from abpoa_tpu import obs
    from abpoa_tpu.obs import trace
    obs.trace_enable()
    rid_a, rid_b = obs.new_request_id(), obs.new_request_id()
    assert rid_a != rid_b and len(rid_a) == 12
    with obs.request_ctx(rid_a, 1):
        with obs.span("dp:jax", "dp", args={"Qp": 2048}):
            pass
    with obs.request_ctx(rid_b):
        obs.instant("mark", "t")
    with obs.span("untagged", "t"):
        pass
    evs_a = trace.tracer().events_for(rid_a)
    assert [e[1] for e in evs_a] == ["dp:jax"]
    assert evs_a[0][7] == (rid_a, 1)
    assert [e[1] for e in trace.tracer().events_for(rid_b)] == ["mark"]
    # the Chrome export renders the tag into args (Perfetto args panel)
    doc = trace.to_chrome_trace(events=evs_a)
    ev = doc["traceEvents"][-1]
    assert ev["args"]["rid"] == rid_a and ev["args"]["attempt"] == 1
    assert ev["args"]["Qp"] == 2048


def test_request_trace_export_bounded(tmp_path):
    from abpoa_tpu import obs
    from abpoa_tpu.obs import trace
    obs.trace_enable()
    d = str(tmp_path / "traces")
    paths = []
    for _ in range(6):
        rid = obs.new_request_id()
        with obs.request_ctx(rid):
            with obs.span("execute", "serve"):
                pass
        p = trace.export_request_trace(d, rid, max_files=4)
        assert p and os.path.exists(p)
        paths.append(p)
    # bounded like the ring: only the newest 4 files survive
    kept = [p for p in paths if os.path.exists(p)]
    assert len(kept) == 4 and kept == paths[-4:]
    with open(paths[-1]) as fp:
        doc = json.load(fp)
    meta = next(e for e in doc["traceEvents"] if e["name"] == "trace_meta")
    assert meta["args"]["request_id"] == paths[-1].split("req-")[1].split(
        ".trace")[0]


def test_sampling_is_deterministic():
    from abpoa_tpu.obs import trace
    rid = "00000000abcd"
    os.environ["ABPOA_TPU_TRACE_SAMPLE"] = "0"
    try:
        assert not trace.sampled(rid)
        os.environ["ABPOA_TPU_TRACE_SAMPLE"] = "1"
        assert trace.sampled(rid)
        os.environ["ABPOA_TPU_TRACE_SAMPLE"] = "0.5"
        # same verdict every time (parent and worker must agree)
        assert len({trace.sampled(rid) for _ in range(10)}) == 1
    finally:
        del os.environ["ABPOA_TPU_TRACE_SAMPLE"]


# --------------------------------------------------------------------- #
# cross-process reconciliation + flight harvest                          #
# --------------------------------------------------------------------- #

def test_pool_trace_reconciles_across_pipe(tmp_path, monkeypatch):
    """The PR-7 trace==timers contract extended over the pipe: a pool
    job's worker-side `job:` span (shipped back with the result, rebased
    onto the parent timeline) sums to within 5% of the parent-observed
    dispatch wall, and both halves carry the same request id."""
    from abpoa_tpu import obs
    from abpoa_tpu.obs import trace
    from abpoa_tpu.parallel import run_batch
    monkeypatch.setenv("ABPOA_TPU_POOL_DELAY_S", "0.5")  # dominate overhead
    files = _sim_files(tmp_path, 1)
    obs.start_run()
    obs.trace_enable()
    out = io.StringIO()
    stats = run_batch(files * 2, _pool_params(2), out)
    assert stats["quarantined"] == 0
    evs = trace.tracer().events()
    pool_jobs = [e for e in evs if e[1] == "pool_job:file"]
    worker_jobs = [e for e in evs if e[1] == "job:file"]
    assert len(pool_jobs) == 2 and len(worker_jobs) == 2
    by_rid = {}
    for e in pool_jobs + worker_jobs:
        assert e[7] is not None, e
        by_rid.setdefault(e[7][0], []).append(e)
    assert len(by_rid) == 2  # one id per set, both halves under it
    for rid, pair in by_rid.items():
        names = sorted(e[1] for e in pair)
        assert names == ["job:file", "pool_job:file"]
        parent = next(e for e in pair if e[1] == "pool_job:file")
        worker = next(e for e in pair if e[1] == "job:file")
        # worker span tree wall within 5% of the parent-observed wall
        assert worker[4] == pytest.approx(parent[4], rel=0.05), (rid, pair)
        # rebasing: the worker span lies inside the parent bracket
        assert worker[3] >= parent[3] - 0.05
        # the pipe boundary is visible: foreign tid = worker pid
        assert worker[5] != parent[5]
    # every set also recorded its admission analog (pool_wait)
    assert sum(1 for e in evs if e[1] == "pool_wait") == 2


def test_flight_harvest_on_worker_kill(tmp_path, monkeypatch):
    """worker_kill:1 -> the supervisor harvests the dead worker's flight
    dump, attaches it to the fault record and the job's archive record;
    the dump names the job (rid, attempt) and the observed death."""
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    from abpoa_tpu.parallel import run_batch
    monkeypatch.setenv("ABPOA_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE", "1")
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_DIR", str(tmp_path / "reports"))
    files = _sim_files(tmp_path, 2)
    obs.start_run()
    rz.inject.configure("worker_kill:1")
    try:
        out = io.StringIO()
        stats = run_batch(files, _pool_params(2), out)
    finally:
        rz.inject.reset()
    assert stats["quarantined"] == 0
    crash = [r for r in obs.report().faults if r["kind"] == "worker_crash"]
    assert crash, obs.report().faults
    dump_path = crash[0].get("dump")
    assert dump_path and os.path.exists(dump_path), crash
    with open(dump_path) as fp:
        dump = json.load(fp)
    assert dump["schema"] == "abpoa-tpu-flight"
    job = dump["job"]
    assert job["kind"] == "file" and job["attempt"] == 1
    assert job["rid"] and job["status"].startswith("died:")
    assert dump["harvest"]["reason"] == "crashed"
    assert dump["harvest"]["request_id"] == job["rid"]
    assert obs.report().counters.get("pool.flight_dumps") == 1
    # the archive record for the killed-then-requeued job references it
    recs = []
    with open(tmp_path / "reports" / "reports.jsonl") as fp:
        recs = [json.loads(ln) for ln in fp]
    hit = [r for r in recs if r.get("dump_file")]
    assert len(hit) == 1 and hit[0]["dump_file"] == dump_path
    assert hit[0]["request_id"] == job["rid"]
    assert all(r.get("request_id") for r in recs
               if r.get("kind") == "pool_job")


def test_flight_harvest_sigsegv_tags_attempts(tmp_path, monkeypatch):
    """worker_sigsegv:2 -> the poison job leaves TWO dumps (one per
    attempt), distinctly tagged — the conflation fix: attempt is carried
    on the dump, the fault records and the merged telemetry."""
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    from abpoa_tpu.parallel import run_batch
    monkeypatch.setenv("ABPOA_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    files = _sim_files(tmp_path, 3)
    obs.start_run()
    rz.inject.configure("worker_sigsegv:2")
    try:
        out = io.StringIO()
        stats = run_batch(files, _pool_params(3), out)
    finally:
        rz.inject.reset()
    assert stats["quarantined"] == 1
    crashes = [r for r in obs.report().faults
               if r["kind"] == "worker_crash" and r.get("dump")]
    assert len(crashes) == 2, obs.report().faults
    attempts = sorted(r["attempt"] for r in crashes)
    assert attempts == [1, 2]
    rids = {r["request_id"] for r in crashes}
    assert len(rids) == 1  # same request, two attempts
    for rec in crashes:
        with open(rec["dump"]) as fp:
            dump = json.load(fp)
        assert dump["harvest"]["attempt"] == rec["attempt"]
    assert obs.report().counters.get("pool.flight_dumps") == 2


# --------------------------------------------------------------------- #
# `why`                                                                  #
# --------------------------------------------------------------------- #

def test_why_golden_dump(capsys):
    """Golden: `abpoa-tpu why` on the checked-in dump renders a verdict
    naming the kill, the killed span and its dispatch rung."""
    from abpoa_tpu.cli import main
    assert main(["why", GOLDEN_DUMP]) == 0
    out = capsys.readouterr().out
    assert "why c0ffee123abc" in out
    assert "verdict:" in out
    assert "hard-killed at the job deadline" in out
    assert "mid `dp:jax`" in out
    assert "Qp=2048/W=256" in out
    assert "flight recorder (worker pid 41287" in out
    assert "open span at death: `dp:jax`" in out
    assert "rss:" in out and "1612 MB" in out


def test_why_request_id_archive_lookup(tmp_path, capsys, monkeypatch):
    """`why <request-id>` resolves the archive record and pulls the
    cross-referenced dump; unknown ids are rc=2 with a clear error."""
    from abpoa_tpu.obs import archive
    from abpoa_tpu.cli import main
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE", "1")
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_DIR", str(tmp_path / "reports"))
    archive.append_record({
        "ts": "2026-08-04T12:00:44Z", "kind": "serve_request",
        "label": "req-17", "request_id": "c0ffee123abc",
        "device": "jax", "status": "timeout", "total_wall_s": 30.04,
        "deadline_s": 30.0, "reads": 0, "faults": 1, "quarantined": 0,
        "dump_file": GOLDEN_DUMP,
    })
    assert main(["why", "c0ffee123abc"]) == 0
    out = capsys.readouterr().out
    assert "status=timeout" in out
    assert "504:" in out
    assert "hard-killed at the job deadline mid `dp:jax`" in out
    assert f"dump: {GOLDEN_DUMP}" in out
    assert main(["why", "ffffffffffff"]) == 2


def test_why_trace_attribution(tmp_path, capsys):
    """A timeout whose budget drained in admission wait gets the
    queue-side verdict, coalesced group size named."""
    from abpoa_tpu.cli import main
    trace = {"traceEvents": [
        {"name": "admission_wait", "cat": "serve", "ph": "X", "ts": 0.0,
         "dur": 28.1e6, "pid": 1, "tid": 1,
         "args": {"rid": "aa00aa00aa00", "coalesced_k": 8, "rung": 2048}},
        {"name": "execute", "cat": "serve", "ph": "X", "ts": 28.1e6,
         "dur": 1.9e6, "pid": 1, "tid": 2,
         "args": {"rid": "aa00aa00aa00"}},
    ]}
    tp = str(tmp_path / "t.trace.json")
    with open(tp, "w") as fp:
        json.dump(trace, fp)
    os.environ["ABPOA_TPU_ARCHIVE_DIR"] = str(tmp_path / "empty")
    try:
        archive_rec = {
            "kind": "serve_request", "request_id": "aa00aa00aa00",
            "status": "timeout", "total_wall_s": 30.0, "deadline_s": 30.0,
        }
        from abpoa_tpu.obs import archive
        os.environ["ABPOA_TPU_ARCHIVE"] = "1"
        archive.append_record(archive_rec)
        assert main(["why", tp]) == 0
    finally:
        del os.environ["ABPOA_TPU_ARCHIVE_DIR"]
        os.environ.pop("ABPOA_TPU_ARCHIVE", None)
    out = capsys.readouterr().out
    assert "504: 28.1 s of 30 s budget spent in admission wait behind " \
           "a coalesced K=8 group" in out
    assert "admission_wait" in out and "execute" in out


def test_why_join_round_verdict():
    """Continuous batching (PR 17): a churned request's verdict names the
    group it boarded and the round it joined — replacing the stale
    pickup-time coalesced-K clause."""
    from abpoa_tpu.obs.why import verdict
    rec = {"status": "ok", "total_wall_s": 1.2, "request_id": "aa",
           "join_round": 4, "join_group": 7}
    assert "joined group 7 at round 4" in verdict(rec, None, None)
    # timeout: the join clause replaces "behind a coalesced K=N group"
    rec = {"status": "timeout", "total_wall_s": 30.0, "deadline_s": 30.0,
           "join_round": 2, "join_group": 3}
    trace = {"traceEvents": [
        {"name": "admission_wait", "cat": "serve", "ph": "X", "ts": 0.0,
         "dur": 29e6, "pid": 1, "tid": 1,
         "args": {"rid": "bb", "coalesced_k": 8}}]}
    v = verdict(rec, trace, None)
    assert "joined group 3 at round 2" in v and "K=8" not in v
    # record missing the fields: the admission_wait span args carry them
    trace2 = {"traceEvents": [
        {"name": "admission_wait", "cat": "serve", "ph": "X", "ts": 0.0,
         "dur": 29e6, "pid": 1, "tid": 1,
         "args": {"rid": "cc", "coalesced_k": 2, "join_round": 5,
                  "join_group": 1}}]}
    rec = {"status": "timeout", "total_wall_s": 30.0, "deadline_s": 30.0}
    assert "joined group 1 at round 5" in verdict(rec, trace2, None)


# --------------------------------------------------------------------- #
# satellites: slo offenders, loadgen ids, serve header + archive lint    #
# --------------------------------------------------------------------- #

def test_slo_prints_budget_burner_ids():
    from abpoa_tpu.obs.slo import evaluate, format_table
    objectives = {"objectives": [
        {"name": "req-wall", "metric": "total_wall_s", "max": 1.0,
         "error_budget": 0.1}]}
    records = [{"total_wall_s": 0.1, "request_id": "fast00000000"}
               for _ in range(8)]
    records += [{"total_wall_s": 30.0, "request_id": "slowaaaaaaaa"},
                {"total_wall_s": 12.0, "label": "req-99"}]
    res = evaluate(objectives, records)
    obj = res["objectives"][0]
    assert obj["violated"] and obj["bad"] == 2
    assert [o["id"] for o in obj["offenders"]] == ["slowaaaaaaaa", "req-99"]
    table = format_table(res)
    assert "burned by: slowaaaaaaaa(30)" in table
    assert "req-99(12)" in table


def test_loadgen_slowest_ids():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "loadgen", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)
    gen = loadgen.LoadGen("http://x", [b""], rate=1.0, n=3)
    gen.requests = [(0.010, "200", "aaa"), (0.500, "504", "bbb"),
                    (0.050, "200", "ccc")]
    for dt, _c, _r in gen.requests:
        gen.sketch.observe(dt)
    s = gen.summary(1.0)
    assert [r["id"] for r in s["slowest"]] == ["bbb", "ccc", "aaa"]
    assert s["slowest"][0] == {"ms": 500.0, "status": "504", "id": "bbb"}


def test_loadgen_churn_baseline_comparison():
    """compare_ab: strict domination needs BOTH a lower p99 and a higher
    goodput; ties or one-sided wins do not pass the churn gate."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "loadgen", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)
    churn = {"ok": 20, "wall_s": 10.0, "latency_ms": {"p99": 800.0}}
    static = {"ok": 15, "wall_s": 10.0, "latency_ms": {"p99": 1500.0}}
    comp = loadgen.compare_ab(churn, static)
    assert comp["dominates"]
    assert comp["goodput_rps"] == {"churn": 2.0, "baseline": 1.5}
    # p99 wins but goodput ties -> no domination
    comp = loadgen.compare_ab(
        {"ok": 15, "wall_s": 10.0, "latency_ms": {"p99": 800.0}}, static)
    assert not comp["dominates"]
    # goodput wins but p99 regresses -> no domination
    comp = loadgen.compare_ab(
        {"ok": 20, "wall_s": 10.0, "latency_ms": {"p99": 1600.0}}, static)
    assert not comp["dominates"]
    # missing percentile (no samples) -> conservative fail
    comp = loadgen.compare_ab(
        {"ok": 20, "wall_s": 10.0, "latency_ms": {"p99": None}}, static)
    assert not comp["dominates"]


def test_serve_request_id_header_and_trace(tmp_path, monkeypatch):
    """In-process server with --trace-dir: every response carries
    X-Abpoa-Request-Id; the archive record carries request_id +
    trace_file; the exported trace brackets admission_wait -> execute ->
    request under one rid."""
    import urllib.request
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE", "1")
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_DIR", str(tmp_path / "reports"))
    from abpoa_tpu.params import Params
    from abpoa_tpu.serve import AlignServer
    abpt = Params()
    abpt.device = "numpy"
    srv = AlignServer(abpt, port=0, workers=1,
                      trace_dir=str(tmp_path / "traces"))
    srv.start(warm="off")
    try:
        base = f"http://{srv.host}:{srv.port}"
        with open(os.path.join(DATA_DIR, "test.fa"), "rb") as fp:
            body = fp.read()
        req = urllib.request.Request(base + "/align", data=body,
                                     method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            rid = r.headers.get("X-Abpoa-Request-Id")
        assert rid and len(rid) == 12
        # malformed body also answers with an id
        import urllib.error
        req = urllib.request.Request(
            base + "/align", data=b"@x\nACGT\n+\nII\n", method="POST")
        try:
            urllib.request.urlopen(req, timeout=30)
            assert False, "poisoned body must 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert e.headers.get("X-Abpoa-Request-Id")
            e.read()
    finally:
        srv.stop()
    recs = []
    with open(tmp_path / "reports" / "reports.jsonl") as fp:
        recs = [json.loads(ln) for ln in fp]
    served = [r for r in recs if r.get("kind") == "serve_request"]
    assert len(served) == 1 and served[0]["request_id"] == rid
    tf = served[0].get("trace_file")
    assert tf and os.path.exists(tf)
    with open(tf) as fp:
        doc = json.load(fp)
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    assert {"admission_wait", "execute", "request"} <= names
    assert all(e["args"]["rid"] == rid for e in spans)
    req_span = next(e for e in spans if e["name"] == "request")
    assert req_span["args"]["status"] == "ok"


def test_sampling_off_overhead_guard():
    """With tracing disabled and sampling off, the PR-15 hooks (request
    context, id minting, flight checks in span()) stay within the PR-6/7
    overhead bound on a warm native run."""
    from abpoa_tpu.native import load
    if load() is None:
        pytest.skip("native host core unavailable (no C++ toolchain)")
    from abpoa_tpu import obs
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    def run_once(ctx):
        abpt = Params()
        abpt.device = "native"
        abpt.finalize()
        t0 = time.perf_counter()
        if ctx:
            with obs.request_ctx(obs.new_request_id()):
                msa_from_file(Abpoa(), abpt, SIM2K, io.StringIO())
        else:
            msa_from_file(Abpoa(), abpt, SIM2K, io.StringIO())
        return time.perf_counter() - t0

    os.environ["ABPOA_TPU_TRACE_SAMPLE"] = "0"
    try:
        obs.trace_disable()
        run_once(False)  # warm
        with_ctx = min(run_once(True) for _ in range(2))
        without = min(run_once(False) for _ in range(2))
    finally:
        del os.environ["ABPOA_TPU_TRACE_SAMPLE"]
    assert with_ctx <= without * 1.25 + 0.05, (with_ctx, without)
