"""Resilient-dispatch tests (ISSUE 8): fault injection, degradation
ladder, watchdog, output guards, memory admission, per-set quarantine.

Every injector must leave the run COMPLETE and CORRECT (healthy sets
byte-match a clean host run) with the failure visible in the report
(`faults` records, `degraded` block, quarantine counters) — and with
injection disarmed the resilience layer must cost nothing measurable
(overhead guard, same contract as the obs guard)."""
import io
import json
import os
import time

import numpy as np
import pytest

from conftest import DATA_DIR

TEST_FA = os.path.join(DATA_DIR, "test.fa")
SIM2K = os.path.join(DATA_DIR, "sim2k.fa")


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    """Injection spec and breaker are process-global: every test starts
    and ends disarmed/closed."""
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    rz.inject.reset()
    rz.breaker().reset()
    rz.set_enabled(True)
    yield
    rz.inject.reset()
    rz.breaker().reset()
    rz.set_enabled(True)
    obs.start_run()


def _native_or_skip():
    from abpoa_tpu.native import load
    if load() is None:
        pytest.skip("native host core unavailable (no C++ toolchain)")


def _run_file(device, path=TEST_FA):
    from abpoa_tpu import obs
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    obs.start_run()
    abpt = Params()
    abpt.device = device
    abpt.finalize()
    out = io.StringIO()
    msa_from_file(Abpoa(), abpt, path, out)
    return out.getvalue(), obs.finalize_report()


# --------------------------------------------------------------------- #
# injector harness                                                       #
# --------------------------------------------------------------------- #

def test_inject_spec_parsing():
    from abpoa_tpu import resilience as rz
    rz.inject.configure("oom:2,hang")
    assert rz.inject.armed("oom") and rz.inject.armed("hang")
    assert not rz.inject.armed("garbage")
    assert rz.inject.fire("oom") and rz.inject.fire("oom")
    assert not rz.inject.fire("oom")      # 2 shots consumed
    assert rz.inject.fire("hang")         # unlimited
    with pytest.raises(ValueError, match="unknown fault-injection kind"):
        rz.inject.configure("frobnicate")
    rz.inject.reset()
    assert not rz.inject.fire("hang")


def test_breaker_demotion_ladder():
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    br = rz.breaker()
    thr = int(os.environ.get("ABPOA_TPU_BREAKER_THRESHOLD", "3"))
    for _ in range(thr):
        br.record_failure("jax", "oom")
    assert br.is_open("jax")
    assert br.effective("jax") == "native"
    assert br.effective("pallas") == "pallas"   # pallas itself is healthy
    for _ in range(thr):
        br.record_failure("pallas", "oom")
    assert br.effective("pallas") == "native"   # pallas -> jax(open) -> native
    for _ in range(thr):
        br.record_failure("native", "native_crash")
    assert br.effective("jax") == "numpy"       # whole ladder walked
    rep = obs.finalize_report()
    assert set(rep["degraded"]) == {"jax", "pallas", "native"}
    assert rep["degraded"]["jax"]["to"] == "native"
    # a new run closes the breakers (run-scoped demotion)
    obs.start_run()
    assert not br.is_open("jax")


def test_breaker_half_open_probe_recloses(monkeypatch):
    """trip -> cooldown -> single probe -> reclose (ISSUE 12): a long-
    lived serve process reclaims a demoted backend after a transient
    fault instead of serving degraded until restart."""
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    monkeypatch.setenv("ABPOA_TPU_BREAKER_COOLDOWN_S", "0.1")
    monkeypatch.setenv("ABPOA_TPU_BREAKER_THRESHOLD", "2")
    br = rz.breaker()
    br.record_failure("jax", "oom")
    br.record_failure("jax", "oom")
    assert br.is_open("jax")
    # before the cooldown: no probe permit, callers short-circuit, and
    # per-read resolution (dispatch._resolve) demotes
    assert br.acquire("jax") is None
    assert br.effective("jax") == "native"
    time.sleep(0.12)
    # cooldown elapsed: exactly ONE caller gets the probe permit, and
    # effective() names the backend again so the per-read path can BE
    # that caller (not just the fused route)
    assert not br.is_open("jax")
    assert br.effective("jax") == "jax"
    assert br.acquire("jax") == "probe"
    # ...and everyone else stays demoted while it runs
    assert br.acquire("jax") is None
    assert br.is_open("jax")
    # a stale pre-open dispatch reporting success must NOT reclose on
    # the probe holder's behalf
    br.record_success("jax", probe=False)
    assert br.is_open("jax")
    # the probe holder succeeds -> reclosed, failures zeroed, degraded
    # block cleared
    br.record_success("jax", probe=True)
    assert not br.is_open("jax")
    assert br.acquire("jax") == "closed"
    assert obs.report().degraded.get("jax") is None
    assert obs.report().counters.get("breaker.reclose.jax") == 1
    # the zeroed failure count means one later blip does not insta-trip
    br.record_failure("jax", "oom")
    assert not br.is_open("jax")


def test_breaker_half_open_probe_failure_reopens(monkeypatch):
    from abpoa_tpu import resilience as rz
    monkeypatch.setenv("ABPOA_TPU_BREAKER_COOLDOWN_S", "0.1")
    monkeypatch.setenv("ABPOA_TPU_BREAKER_THRESHOLD", "2")
    br = rz.breaker()
    br.record_failure("jax", "oom")
    br.record_failure("jax", "oom")
    time.sleep(0.12)
    assert br.acquire("jax") == "probe"
    # a stale non-probe failure while open must not touch probe state
    br.record_failure("jax", "oom", probe=False)
    assert br.open["jax"]["probing"]
    # the probe itself fails -> reopened with a fresh cooldown
    br.record_failure("jax", "hang", probe=True)
    assert br.is_open("jax")
    assert br.acquire("jax") is None
    from abpoa_tpu import obs
    assert obs.report().counters.get("breaker.probe_fail.jax") == 1
    # the next cooldown hands out a new probe; success recovers
    time.sleep(0.12)
    assert br.acquire("jax") == "probe"
    br.record_success("jax", probe=True)
    assert not br.is_open("jax")


def test_breaker_probe_through_guarded_dispatch(monkeypatch):
    """End-to-end: guarded_device_call claims the probe permit, a healthy
    dispatch recloses the breaker, and the pre-reclose short-circuit
    behavior is preserved inside the cooldown."""
    from abpoa_tpu import resilience as rz
    monkeypatch.setenv("ABPOA_TPU_BREAKER_COOLDOWN_S", "0.1")
    monkeypatch.setenv("ABPOA_TPU_BREAKER_THRESHOLD", "1")
    br = rz.breaker()
    br.record_failure("jax", "oom")
    assert br.is_open("jax")
    with pytest.raises(rz.DispatchFailed) as ei:
        rz.guarded_device_call("t", "jax", lambda: "never")
    assert ei.value.kind == "breaker_open"
    time.sleep(0.12)
    assert rz.guarded_device_call("t", "jax", lambda: "ok") == "ok"
    assert not br.is_open("jax")


def test_breaker_abort_probe_on_unclassified(monkeypatch):
    """An unclassified exception during the probe must release the permit
    (breaker stays open, cooldown restarts) — never wedge 'probing'."""
    from abpoa_tpu import resilience as rz
    monkeypatch.setenv("ABPOA_TPU_BREAKER_COOLDOWN_S", "0.1")
    monkeypatch.setenv("ABPOA_TPU_BREAKER_THRESHOLD", "1")
    br = rz.breaker()
    br.record_failure("jax", "oom")
    time.sleep(0.12)
    with pytest.raises(TypeError):
        rz.guarded_device_call(
            "t", "jax", lambda: (_ for _ in ()).throw(TypeError("bug")))
    assert br.is_open("jax")          # still demoted
    assert not br.open["jax"]["probing"]   # but not wedged probing
    time.sleep(0.12)
    assert br.acquire("jax") == "probe"    # next cooldown probes again


def test_watchdog_deadline():
    from abpoa_tpu import resilience as rz
    assert rz.watchdog.call_with_deadline(lambda: 41 + 1, 5.0) == 42
    with pytest.raises(ZeroDivisionError):
        rz.watchdog.call_with_deadline(lambda: 1 // 0, 5.0)
    with pytest.raises(rz.DispatchTimeout):
        rz.watchdog.call_with_deadline(lambda: time.sleep(3), 0.1,
                                       label="t")
    # deadline 0 = supervision off: direct call, no thread
    assert rz.watchdog.call_with_deadline(lambda: "x", 0) == "x"


def test_classify_exceptions():
    from abpoa_tpu import resilience as rz
    assert rz.classify(rz.InjectedDeviceOOM("x"))[0] == "oom"
    assert rz.classify(RuntimeError("RESOURCE_EXHAUSTED: oom"))[0] == "oom"
    assert rz.classify(RuntimeError("XLA compilation failed"))[0] \
        == "compile_fail"
    kind, retryable, breaks = rz.classify(
        RuntimeError("fused loop: 3 sequential-fusion fallbacks"))
    assert kind == "fused_bail" and not retryable and not breaks
    assert rz.classify(rz.DispatchTimeout("t"))[0] == "hang"
    assert rz.classify(TypeError("bug")) is None    # real bugs propagate


# --------------------------------------------------------------------- #
# output guards                                                          #
# --------------------------------------------------------------------- #

def test_guard_cigar_invariants():
    from abpoa_tpu import constants as C
    from abpoa_tpu.align.result import AlignResult
    from abpoa_tpu.cigar import push_cigar
    from abpoa_tpu.params import Params
    from abpoa_tpu.resilience.guards import align_result_violation
    abpt = Params().finalize()
    res = AlignResult()
    for q in range(4):
        push_cigar(res.cigar, C.CMATCH, 1, q + 2, q)
    res.best_score = 8
    assert align_result_violation(res, 4, 10, abpt) is None
    # truncated cigar: global mode must consume the whole query
    res.cigar = res.cigar[:2]
    assert "consumes 2 of 4" in align_result_violation(res, 4, 10, abpt)
    # absurd score
    res2 = AlignResult()
    res2.best_score = 1 << 40
    assert "int32" in align_result_violation(res2, 4, 10, abpt)
    # over-consumption of graph nodes
    res3 = AlignResult()
    push_cigar(res3.cigar, C.CDEL, 50, 2, 0)
    res3.best_score = 0
    assert "graph nodes" in align_result_violation(res3, 4, 10, abpt)


def test_guard_never_raises_on_unpackable_cigar():
    """A cigar with a negative entry (int64 backtrack gone wrong — the
    exact bit-flip threat model) is a VIOLATION, not an OverflowError out
    of the guard."""
    from abpoa_tpu.align.result import AlignResult
    from abpoa_tpu.params import Params
    from abpoa_tpu.resilience.guards import align_result_violation
    abpt = Params().finalize()
    res = AlignResult()
    res.cigar = [-5]
    res.best_score = 0
    assert "uint64" in align_result_violation(res, 4, 10, abpt)


def test_breaker_short_circuits_dispatch():
    """An open breaker fails a guarded dispatch fast — the first attempt
    (a full watchdog deadline on a wedged backend) is not re-paid."""
    from abpoa_tpu import resilience as rz
    br = rz.breaker()
    for _ in range(int(os.environ.get("ABPOA_TPU_BREAKER_THRESHOLD", "3"))):
        br.record_failure("jax", "hang")
    calls = []
    with pytest.raises(rz.DispatchFailed) as ei:
        rz.guarded_device_call("t", "jax", lambda: calls.append(1))
    assert ei.value.kind == "breaker_open"
    assert not calls, "dispatch attempted despite an open breaker"


def test_graph_base_guard():
    from abpoa_tpu.resilience.guards import GarbageOutput, check_graph_bases
    check_graph_bases(np.array([0, 1, 2, 3, 4]), 5)
    with pytest.raises(GarbageOutput):
        check_graph_bases(np.array([0, 99]), 5)


# --------------------------------------------------------------------- #
# end-to-end injection: each armed injector completes degraded + correct #
# --------------------------------------------------------------------- #

def test_garbage_injection_native_rerun():
    """A corrupted native dispatch result trips the output guard and
    re-runs that read on the host oracle; output stays byte-correct."""
    _native_or_skip()
    from abpoa_tpu import resilience as rz
    want, _ = _run_file("native")
    rz.inject.configure("garbage:1")
    got, rep = _run_file("native")
    assert got == want
    assert rep["faults"]["kinds"] == {"garbage_output": 1}
    assert rep["counters"]["guard.dp_violation"] == 1
    assert rep["counters"]["dispatch.rerun.numpy"] == 1


@pytest.mark.parametrize("kind", ["compile_fail", "oom"])
def test_device_failure_degrades_to_host(kind, monkeypatch):
    """With the injector armed on every device dispatch, the run demotes
    jax -> host through the circuit breaker and completes with output
    identical to the numpy oracle."""
    monkeypatch.setenv("ABPOA_TPU_BREAKER_THRESHOLD", "2")
    from abpoa_tpu import resilience as rz
    want, _ = _run_file("numpy")
    rz.inject.configure(kind)
    got, rep = _run_file("jax")
    assert got == want
    assert kind in rep["faults"]["kinds"]
    assert rep["degraded"]["jax"]["to"] in ("native", "numpy")
    assert rep["counters"]["breaker.open.jax"] == 1
    # the injected failure fires before any kernel runs: zero compiles paid
    assert rep["counters"][f"inject.{kind}"] >= 2


def test_hang_injection_watchdog_degrades(monkeypatch):
    """An injected dispatch hang trips the watchdog deadline; the run
    degrades and completes instead of blocking forever."""
    monkeypatch.setenv("ABPOA_TPU_WATCHDOG_S", "0.3")
    monkeypatch.setenv("ABPOA_TPU_INJECT_HANG_S", "1.0")
    monkeypatch.setenv("ABPOA_TPU_BREAKER_THRESHOLD", "2")
    from abpoa_tpu import resilience as rz
    want, _ = _run_file("numpy")
    rz.inject.configure("hang")
    t0 = time.perf_counter()
    got, rep = _run_file("jax")
    wall = time.perf_counter() - t0
    assert got == want
    assert rep["faults"]["kinds"].get("hang", 0) >= 2
    assert rep["counters"]["watchdog.timeouts"] >= 2
    assert rep["degraded"]["jax"]["to"] in ("native", "numpy")
    assert wall < 30, "watchdog did not bound the hang"


def test_fused_garbage_graph_guard(monkeypatch):
    """Garbage injected into the fused loop's downloaded graph is caught
    by the alphabet guard; the run falls back to the host loop and the
    output still matches the oracle."""
    monkeypatch.setenv("ABPOA_TPU_BREAKER_THRESHOLD", "99")
    from abpoa_tpu import resilience as rz
    want, _ = _run_file("numpy")
    rz.inject.configure("garbage")
    got, rep = _run_file("jax")
    assert got == want
    assert rep["faults"]["kinds"].get("garbage_output", 0) >= 1


# --------------------------------------------------------------------- #
# memory admission control                                               #
# --------------------------------------------------------------------- #

def test_memory_estimate_model():
    from abpoa_tpu import constants as C
    from abpoa_tpu.resilience import memory
    caps = dict(N=4096, E=8, A=8, W=512, Qp=2304, reads=32, K=1,
                plane16=True, gap_mode=C.CONVEX_GAP, m=5)
    one = memory.estimate_bytes(caps)
    assert one > 0
    assert memory.estimate_bytes(dict(caps, K=8)) == 8 * one
    assert memory.estimate_bytes(dict(caps, plane16=False)) > one


def test_admission_decisions(monkeypatch):
    from abpoa_tpu import constants as C
    from abpoa_tpu.resilience import memory
    caps = dict(N=4096, E=8, A=8, W=512, Qp=2304, reads=32, K=4,
                plane16=True, gap_mode=C.CONVEX_GAP, m=5)
    per_set = memory.per_set_bytes(caps)
    # budget for ~2 sets: chunk
    monkeypatch.setenv("ABPOA_TPU_MEM_BUDGET_MB",
                       str(2.5 * per_set / 1e6))
    decision, _est, _b = memory.admit(caps)
    assert decision == "chunk"
    assert memory.max_sets_within(caps) == 2
    # rung-aware chunking: the dispatch pads K to k_rung (pow2), and the
    # padding slots allocate real planes — a budget of 5.5 sets admits
    # k=4 (rung 4), NOT k=5 (rung 8 would allocate 8 sets' planes)
    caps6 = dict(caps, K=6)
    monkeypatch.setenv("ABPOA_TPU_MEM_BUDGET_MB",
                       str(5.5 * per_set / 1e6))
    assert memory.max_sets_within(caps6) == 4
    # budget below one set: demote
    monkeypatch.setenv("ABPOA_TPU_MEM_BUDGET_MB",
                       str(0.5 * per_set / 1e6))
    assert memory.admit(caps)[0] == "demote"
    # 0 disables admission
    monkeypatch.setenv("ABPOA_TPU_MEM_BUDGET_MB", "0")
    assert memory.admit(caps)[0] == "ok"


def test_admission_demotes_fused_before_dispatch(monkeypatch):
    """A device run whose planes exceed the budget is demoted to the host
    loop BEFORE any device dispatch (no OOM, no compile), with the
    decision visible as a faults record."""
    monkeypatch.setenv("ABPOA_TPU_MEM_BUDGET_MB", "0.001")
    from abpoa_tpu import obs
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, _ingest_records, _run_fused_device
    obs.start_run()
    abpt = Params()
    abpt.device = "jax"
    abpt.finalize()
    ab = Abpoa()
    seqs, weights = _ingest_records(ab, abpt, read_fastx(TEST_FA))
    assert _run_fused_device(ab, abpt, seqs, weights, 0) is False
    rep = obs.finalize_report()
    assert rep["faults"]["kinds"] == {"admission": 1}
    assert rep["counters"]["admission.demote"] == 1


# --------------------------------------------------------------------- #
# per-set quarantine: malformed-input fuzz grid                          #
# --------------------------------------------------------------------- #

def _write(path, data):
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(path, mode) as fp:
        fp.write(data)
    return str(path)


def _poison_cases(tmp_path):
    """(name, file, should_quarantine) — every malformed shape must give
    a structured per-set error; benign oddities must still align."""
    return [
        ("truncated_fastq",
         _write(tmp_path / "t.fq", "@r1\nACGTACGTAC\n+\n"), True),
        ("qual_len_mismatch",
         _write(tmp_path / "q.fq", "@r1\nACGTACGTAC\n+\nIIII\n"), True),
        ("empty_sequence",
         _write(tmp_path / "e.fa", ">a\n\n>b\nACGT\n"), True),
        ("empty_file", _write(tmp_path / "z.fa", ""), True),
        ("missing_file", str(tmp_path / "nope.fa"), True),
        ("binary_junk",
         _write(tmp_path / "b.fa", b"\x1f\x8b\x00garbage-not-gzip"), True),
        ("over_reads_cap",
         _write(tmp_path / "big.fa",
                "".join(f">r{i}\nACGTACGT\n" for i in range(9))), True),
        ("crlf_endings",
         _write(tmp_path / "crlf.fa",
                "".join(ln + "\r\n" for ln in
                        open(TEST_FA).read().splitlines())), False),
        ("non_acgt_bytes",
         _write(tmp_path / "n.fa",
                ">a\nACGTNRYACGT\n>b\nACGTNNAACGT\n>c\nACGTNRAACGT\n"),
         False),
    ]


def test_quarantine_fuzz_grid(tmp_path, monkeypatch):
    """The `-l` batch path over the full malformed-input grid: every
    poisoned set produces a structured per-set error (faults record with
    its set index), every healthy set completes, nothing raises."""
    monkeypatch.setenv("ABPOA_TPU_MAX_READS", "8")   # arm over_reads_cap
    from abpoa_tpu import obs
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    cases = _poison_cases(tmp_path)
    files = [TEST_FA] + [path for _n, path, _q in cases] + [TEST_FA]
    n_bad = sum(1 for _n, _p, q in cases if q)
    n_good = len(files) - n_bad
    obs.start_run()
    abpt = Params()
    abpt.device = "numpy"
    abpt.finalize()
    out = io.StringIO()
    stats = run_batch(files, abpt, out)
    assert stats == {"sets": len(files), "quarantined": n_bad}
    assert out.getvalue().count(">Consensus_sequence") == n_good
    rep = obs.finalize_report()
    assert rep["counters"]["quarantine.sets"] == n_bad
    recs = [r for r in rep["faults"]["records"]
            if r["kind"] == "poisoned_set"]
    bad_idx = sorted(1 + i for i, (_n, _p, q) in enumerate(cases) if q)
    assert sorted(r["set"] for r in recs) == bad_idx
    assert all(r.get("detail") for r in recs)


def test_crlf_output_matches_lf(tmp_path):
    """CRLF line endings must parse to the same records (a stray '\\r'
    would otherwise encode as an ambiguous base and shift the consensus)."""
    crlf = _write(tmp_path / "crlf.fa",
                  "".join(ln + "\r\n" for ln in
                          open(TEST_FA).read().splitlines()))
    want, _ = _run_file("numpy", TEST_FA)
    got, _ = _run_file("numpy", crlf)
    assert got == want


def test_single_file_poisoned_cli_rc(tmp_path):
    """A poisoned single-input CLI run: structured one-line error, rc=1,
    no traceback. An all-quarantined -l run also fails (rc=1)."""
    from abpoa_tpu.cli import main
    bad = _write(tmp_path / "bad.fa", ">a\n\n")
    assert main([bad, "--device", "numpy",
                 "-o", str(tmp_path / "o.fa")]) == 1
    lst = _write(tmp_path / "l.txt", bad + "\n")
    assert main(["-l", lst, "--device", "numpy",
                 "-o", str(tmp_path / "o2.fa")]) == 1
    # one healthy set among poisoned ones -> rc 0
    lst2 = _write(tmp_path / "l2.txt", bad + "\n" + TEST_FA + "\n")
    assert main(["-l", lst2, "--device", "numpy",
                 "-o", str(tmp_path / "o3.fa")]) == 0


def test_poison_set_injection(tmp_path):
    """The poison_set injector quarantines one set without any malformed
    file on disk (the chaos-smoke CI hook)."""
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    rz.inject.configure("poison_set:1")
    obs.start_run()
    abpt = Params()
    abpt.device = "numpy"
    abpt.finalize()
    out = io.StringIO()
    stats = run_batch([TEST_FA, TEST_FA], abpt, out)
    assert stats["quarantined"] == 1
    assert out.getvalue().count(">Consensus_sequence") == 1
    rep = obs.finalize_report()
    assert rep["counters"]["inject.poison_set"] == 1
    assert rep["faults"]["kinds"]["poisoned_set"] == 1


def test_msa_batch_quarantines_poisoned_set():
    """pyapi.msa_batch: a poisoned set returns None in its slot (reported
    per set), the remaining sets complete with correct results."""
    import abpoa_tpu.pyapi as pa
    from abpoa_tpu import obs  # noqa: F401
    sets = [["ACGTACGTAA", "ACGTACGTA", "ACGTTCGTAA"],
            ["ACGTACGTAA", "", "ACGTTCGTAA"],       # poisoned: empty read
            ["TTGCAACGTA", "TTGCAACGT", "TTGCATCGTA"]]
    a = pa.msa_aligner(device="numpy")
    batch = a.msa_batch(sets, out_cons=True, out_msa=False)
    assert batch[1] is None
    for k in (0, 2):
        want = pa.msa_aligner(device="numpy").msa(sets[k], True, False)
        assert batch[k].cons_seq == want.cons_seq
    rep = a.last_report
    assert rep["faults"]["kinds"]["poisoned_set"] == 1
    assert rep["counters"]["quarantine.sets"] == 1


# --------------------------------------------------------------------- #
# report viewer + schema                                                 #
# --------------------------------------------------------------------- #

def test_faults_cap_and_drops():
    import importlib
    R = importlib.import_module("abpoa_tpu.obs.report")
    rep = R.RunReport()
    for i in range(R.FAULTS_CAP + 10):
        rep.record_fault("oom", backend="jax", detail=f"f{i}")
    blk = rep._faults_block()
    assert blk["count"] == R.FAULTS_CAP + 10
    assert blk["dropped"] == 10
    assert len(blk["records"]) == R.FAULTS_CAP
    assert rep.counters["faults.oom"] == R.FAULTS_CAP + 10


def test_report_viewer_renders_faults():
    from abpoa_tpu.obs.report import RunReport, render_report
    rep = RunReport()
    rep.record_fault("oom", backend="jax", detail="RESOURCE_EXHAUSTED",
                     action="retry")
    rep.record_fault("poisoned_set", set_index=3, detail="empty sequence",
                     action="quarantined")
    rep.mark_degraded("jax", "native", "oom", 3)
    text = render_report(rep.as_dict())
    assert "faults: 2" in text
    assert "oom" in text and "set 3" in text
    assert "degraded (circuit breakers open at end of run):" in text
    assert "jax -> native" in text
    assert "quarantined sets: 1" in text


# --------------------------------------------------------------------- #
# overhead: disarmed resilience must cost nothing measurable             #
# --------------------------------------------------------------------- #

def test_host_path_never_spawns_watchdog(monkeypatch):
    """Structural no-new-syncs guard: with injection disarmed, a host-
    backend run must never route through the watchdog worker (no threads,
    no deadline waits on the hot path)."""
    _native_or_skip()
    from abpoa_tpu.resilience import watchdog

    def boom(*a, **kw):
        raise AssertionError("watchdog used on a host dispatch")

    monkeypatch.setattr(watchdog, "call_with_deadline", boom)
    out, rep = _run_file("native", SIM2K)
    assert out.startswith(">")
    assert rep["counters"]["dispatch.native"] > 0


def test_overhead_guard_resilience_disarmed():
    """Warm sim2k wall with the resilience envelope active (guards +
    injection checks, disarmed) stays within noise of the kill switch —
    the <2% intent of the acceptance bar, asserted with the same loose
    scheduler-jitter bound the obs overhead guard uses."""
    _native_or_skip()
    from abpoa_tpu import resilience as rz
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    def run_once():
        abpt = Params()
        abpt.device = "native"
        abpt.finalize()
        t0 = time.perf_counter()
        msa_from_file(Abpoa(), abpt, SIM2K, io.StringIO())
        return time.perf_counter() - t0

    run_once()  # warm
    try:
        rz.set_enabled(True)
        on = min(run_once() for _ in range(3))
        rz.set_enabled(False)
        off = min(run_once() for _ in range(3))
    finally:
        rz.set_enabled(True)
    assert on <= off * 1.25 + 0.05, (on, off)


def test_watchdog_abandon_gauge_and_pool_hard_kill_routing(monkeypatch,
                                                           capsys):
    """ISSUE-13 satellite: the abandoned-thread leak is gauged
    (abpoa_watchdog_abandoned_threads) and warns past
    ABPOA_TPU_WATCHDOG_ABANDON_MAX; inside a pool worker thread
    supervision is OFF — the supervisor's SIGKILL is the deadline."""
    from abpoa_tpu.obs import metrics
    from abpoa_tpu.resilience import watchdog as wd

    # pool workers never thread-supervise (hard kill replaces abandon) —
    # unless explicitly forced
    monkeypatch.setenv("ABPOA_TPU_POOL_WORKER", "1")
    assert wd.supervision_needed("jax") is False
    monkeypatch.setenv("ABPOA_TPU_WATCHDOG_FORCE", "1")
    assert wd.supervision_needed("jax") is True
    monkeypatch.delenv("ABPOA_TPU_WATCHDOG_FORCE")
    monkeypatch.delenv("ABPOA_TPU_POOL_WORKER")

    monkeypatch.setenv("ABPOA_TPU_WATCHDOG_ABANDON_MAX", "0")
    before = wd.abandoned_count()
    with pytest.raises(wd.DispatchTimeout):
        wd.call_with_deadline(lambda: time.sleep(0.8), deadline_s=0.05,
                              label="abandon-gauge-test")
    g = metrics.registry().get("abpoa_watchdog_abandoned_threads")
    assert g is not None and g.value() >= before + 1
    assert wd._WARNED_LEAK is True
    assert "abandoned watchdog threads" in capsys.readouterr().err
