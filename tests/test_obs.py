"""Run-telemetry subsystem tests (ISSUE 5 tentpole).

- schema golden: the report's top-level keys are stable and versioned
- end-to-end sim2k: phases cover >=90% of wall, dispatch/band/cell
  counters are nonzero, the CLI --report flag emits the same schema
- lockstep `-l` run: lockstep group/chunk counters and the fused phase
- overhead guard: warm sim2k wall with reporting on is within noise of off
- MFU model: the estimate appears exactly when a known device kind ran
"""
import io
import json
import os
import time

import numpy as np
import pytest

from conftest import DATA_DIR

SIM2K = os.path.join(DATA_DIR, "sim2k.fa")


def _native_or_skip():
    from abpoa_tpu.native import load
    if load() is None:
        pytest.skip("native host core unavailable (no C++ toolchain)")


def test_report_schema_golden():
    """Top-level schema is goldened: any key change is a SCHEMA_VERSION
    bump (downstream consumers: bench.py, chip_watcher, BENCH_onchip)."""
    from abpoa_tpu import obs
    from abpoa_tpu.pyapi import msa_aligner
    a = msa_aligner(device="numpy")
    assert a.last_report is None
    res = a.msa(["ACGTACGTAA", "ACGTACGTA", "ACGTTCGTAA"], True, False)
    assert res.n_cons == 1
    rep = a.last_report
    assert tuple(rep.keys()) == obs.SCHEMA_KEYS
    assert rep["schema"] == obs.SCHEMA
    assert rep["schema_version"] == obs.SCHEMA_VERSION == 1
    assert rep["counters"]["dispatch.numpy"] == 2
    assert rep["counters"]["dp.cells"] > 0
    assert {"align", "fusion", "consensus"} <= set(rep["phases"])
    for ph in rep["phases"].values():
        assert set(ph) == {"wall_s", "calls"}
    assert rep["phase_wall_sum_s"] <= rep["total_wall_s"] + 1e-6
    band = rep["values"]["dp.band_width"]
    assert set(band) == {"count", "sum", "min", "max"} and band["max"] > 0
    # summary() is the compact embedding bench/chip_watcher commit
    s = obs.summary(rep)
    assert set(s) == {"schema_version", "phases", "dp_cells",
                      "cell_updates_per_sec", "mfu"}
    assert s["dp_cells"] == rep["counters"]["dp.cells"]


def test_cli_report_sim2k(tmp_path):
    """Acceptance: `abpoa-tpu sim2k.fa --report r.json` emits a versioned
    report whose phase wall-times sum to >=90% of total wall with nonzero
    dispatch/band/cell counters."""
    _native_or_skip()
    from abpoa_tpu.cli import main
    rpt = str(tmp_path / "r.json")
    out = str(tmp_path / "cons.fa")
    rc = main([SIM2K, "--device", "native", "-o", out, "--report", rpt])
    assert rc == 0
    with open(rpt) as fp:
        rep = json.load(fp)
    assert rep["schema_version"] == 1
    assert rep["counters"]["dispatch.native"] > 0
    assert rep["counters"]["dp.cells"] > 0
    assert rep["values"]["dp.band_width"]["max"] > 0
    assert rep["phase_wall_sum_s"] >= 0.9 * rep["total_wall_s"], rep
    with open(out) as fp:
        assert fp.read().startswith(">")


def test_lockstep_report_counters():
    """A `-l` lockstep run (CPU jax backend) reports batch K, chunk count,
    finished-set no-op fraction, and the fused align phase."""
    import jax  # noqa: F401  (virtual CPU mesh from conftest)
    from abpoa_tpu import obs
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    obs.start_run()
    abpt = Params()
    abpt.device = "jax"
    abpt.finalize()
    out = io.StringIO()
    run_batch([os.path.join(DATA_DIR, "test.fa"),
               os.path.join(DATA_DIR, "test.fa")], abpt, out)
    assert out.getvalue().count(">Consensus_sequence") == 2
    rep = obs.finalize_report()
    assert rep["counters"]["lockstep.groups"] == 1
    assert rep["counters"]["lockstep.chunks"] >= 1
    assert rep["values"]["lockstep.k"]["max"] == 2
    assert "lockstep.noop_set_fraction" in rep["values"]
    assert "align_fused" in rep["phases"]
    assert rep["counters"]["dp.cells"] > 0


def test_overhead_guard_sim2k():
    """Reporting must be free: warm sim2k wall with telemetry enabled
    stays within noise of disabled (counters are host-side dict updates,
    never device syncs). Bound is deliberately loose — this guards against
    an accidental hot-loop sync, not scheduler jitter."""
    _native_or_skip()
    from abpoa_tpu import obs
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    def run_once():
        abpt = Params()
        abpt.device = "native"
        abpt.finalize()
        t0 = time.perf_counter()
        msa_from_file(Abpoa(), abpt, SIM2K, io.StringIO())
        return time.perf_counter() - t0

    run_once()  # warm: .so load, file cache
    try:
        obs.set_enabled(True)
        on = min(run_once() for _ in range(2))
        obs.set_enabled(False)
        off = min(run_once() for _ in range(2))
    finally:
        obs.set_enabled(True)
    assert on <= off * 1.25 + 0.05, (on, off)


def test_disabled_report_is_empty():
    from abpoa_tpu import obs
    try:
        obs.start_run()
        obs.set_enabled(False)
        with obs.phase("align"):
            pass
        obs.count("dispatch.numpy")
        obs.observe("dp.band_width", 3)
        obs.record_dp(10, 10, 2)
    finally:
        obs.set_enabled(True)
    rep = obs.finalize_report()
    assert rep["phases"] == {} and rep["counters"] == {}
    assert rep["values"] == {} and rep["mfu"] is None


def test_mfu_model():
    from abpoa_tpu import constants as C
    from abpoa_tpu.obs.mfu import (CELL_INT_OPS, mfu_block,
                                   peak_ops_for_kind)
    from abpoa_tpu.obs.report import RunReport
    assert peak_ops_for_kind("TPU v4") == 275e12
    # both libtpu spellings of the lite chips resolve
    assert peak_ops_for_kind("TPU v5 lite") == 394e12
    assert peak_ops_for_kind("TPU v5e") == 394e12
    assert peak_ops_for_kind("TPU v6 lite") == 918e12
    assert peak_ops_for_kind("TPU v5p") == 459e12
    assert peak_ops_for_kind("TPU v9x") is None  # unknown stays None
    rep = RunReport()
    rep.phases["align_fused"] = [2.0, 1]
    rep.counters["dp.cells"] = 10_000_000
    rep.counters["dp.cell_ops"] = 10_000_000 * CELL_INT_OPS[C.CONVEX_GAP]
    # CPU device: throughput yes, MFU no
    blk = mfu_block(rep, {"platform": "cpu", "kind": ""})
    assert blk["cell_updates_per_sec"] == 5_000_000
    assert blk["mfu"] is None
    # known TPU kind: MFU appears
    blk = mfu_block(rep, {"platform": "tpu", "kind": "TPU v4"})
    assert blk["peak_ops_per_sec"] == 275e12
    assert blk["mfu"] == pytest.approx(
        10_000_000 * CELL_INT_OPS[C.CONVEX_GAP] / 2.0 / 275e12, rel=1e-4)
    # no cells recorded -> no block at all
    assert mfu_block(RunReport(), None) is None


def test_phred_vec_used_by_native_cons_matches_python():
    """The native fast path's phred column must match the Python consensus
    path byte for byte (it now shares the scalar phred)."""
    _native_or_skip()
    from abpoa_tpu.cons.consensus import phred_score, phred_score_vec
    cov = np.array([0, 1, 5, 17, 20], dtype=np.int64)
    assert phred_score_vec(cov, 20).tolist() == [
        phred_score(int(c), 20) for c in cov]


def test_device_capture_noop_without_dir(tmp_path):
    """Capture hooks never interfere when unarmed, and arm/disarm works."""
    from abpoa_tpu import obs
    with obs.device_capture("x"):
        pass  # unarmed: pure no-op
    d = str(tmp_path / "prof")
    obs.set_profile_dir(d)
    try:
        assert obs.profile_dir() == d and os.path.isdir(d)
    finally:
        obs.set_profile_dir(None)
    assert obs.profile_dir() is None
