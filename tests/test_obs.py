"""Run-telemetry subsystem tests (ISSUE 5 tentpole + ISSUE 6 event layer).

- schema golden: the report's top-level keys are stable and versioned
- end-to-end sim2k: phases cover >=90% of wall, dispatch/band/cell
  counters are nonzero, the CLI --report flag emits the same schema
- lockstep `-l` run: lockstep group/chunk counters and the fused phase
- overhead guard: warm sim2k wall with reporting on (and with tracing on)
  is within noise of off
- MFU model: the estimate appears exactly when a known device kind ran
- trace golden (ISSUE 6): `--trace` emits valid Chrome trace-event JSON
  whose phase-span totals reconcile with the report phase timers
- compile log: a second identical-bucket dispatch records a cache hit
- perf gate: tools/perf_gate.py exit status flips on an injected
  regression past the threshold
"""
import io
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from conftest import DATA_DIR

SIM2K = os.path.join(DATA_DIR, "sim2k.fa")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _native_or_skip():
    from abpoa_tpu.native import load
    if load() is None:
        pytest.skip("native host core unavailable (no C++ toolchain)")


def test_report_schema_golden():
    """Top-level schema is goldened: any key change is a SCHEMA_VERSION
    bump (downstream consumers: bench.py, chip_watcher, BENCH_onchip)."""
    from abpoa_tpu import obs
    from abpoa_tpu.pyapi import msa_aligner
    a = msa_aligner(device="numpy")
    assert a.last_report is None
    res = a.msa(["ACGTACGTAA", "ACGTACGTA", "ACGTTCGTAA"], True, False)
    assert res.n_cons == 1
    rep = a.last_report
    assert tuple(rep.keys()) == obs.SCHEMA_KEYS
    assert rep["schema"] == obs.SCHEMA
    assert rep["schema_version"] == obs.SCHEMA_VERSION == 4
    # v3: a clean run carries no fault history and no demotions
    assert rep["faults"] is None and rep["degraded"] is None
    assert rep["counters"]["dispatch.numpy"] == 2
    assert rep["counters"]["dp.cells"] > 0
    assert {"align", "fusion", "consensus"} <= set(rep["phases"])
    for ph in rep["phases"].values():
        assert set(ph) == {"wall_s", "calls"}
    assert rep["phase_wall_sum_s"] <= rep["total_wall_s"] + 1e-6
    band = rep["values"]["dp.band_width"]
    assert set(band) == {"count", "sum", "min", "max"} and band["max"] > 0
    # v2: per-read latency records (one per input read, none amortized
    # on the per-read host path)
    reads = rep["reads"]
    assert reads["count"] == 3 and reads["dropped"] == 0
    assert reads["backends"] == {"numpy": 3}
    wm = reads["wall_ms"]
    assert 0 < wm["p50"] <= wm["p95"] <= wm["p99"] <= wm["max"]
    # summary() is the compact embedding bench/chip_watcher commit
    s = obs.summary(rep)
    assert set(s) == {"schema_version", "phases", "dp_cells",
                      "cell_updates_per_sec", "mfu", "read_wall_ms"}
    assert s["dp_cells"] == rep["counters"]["dp.cells"]
    assert s["read_wall_ms"] == {q: wm[q] for q in ("p50", "p95", "p99")}


def test_cli_report_sim2k(tmp_path):
    """Acceptance: `abpoa-tpu sim2k.fa --report r.json` emits a versioned
    report whose phase wall-times sum to >=90% of total wall with nonzero
    dispatch/band/cell counters."""
    _native_or_skip()
    from abpoa_tpu.cli import main
    rpt = str(tmp_path / "r.json")
    out = str(tmp_path / "cons.fa")
    rc = main([SIM2K, "--device", "native", "-o", out, "--report", rpt])
    assert rc == 0
    with open(rpt) as fp:
        rep = json.load(fp)
    assert rep["schema_version"] == 4
    assert rep["counters"]["dispatch.native"] > 0
    assert rep["counters"]["dp.cells"] > 0
    assert rep["values"]["dp.band_width"]["max"] > 0
    assert rep["phase_wall_sum_s"] >= 0.9 * rep["total_wall_s"], rep
    with open(out) as fp:
        assert fp.read().startswith(">")


def test_lockstep_report_counters():
    """A `-l` lockstep run (CPU jax backend) reports batch K, chunk count,
    finished-set no-op fraction, and the fused align phase."""
    import jax  # noqa: F401  (virtual CPU mesh from conftest)
    from abpoa_tpu import obs
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    obs.start_run()
    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"  # CPU-only host: lockstep is opt-in (round 8)
    abpt.finalize()
    out = io.StringIO()
    run_batch([os.path.join(DATA_DIR, "test.fa"),
               os.path.join(DATA_DIR, "test.fa")], abpt, out)
    assert out.getvalue().count(">Consensus_sequence") == 2
    rep = obs.finalize_report()
    assert rep["counters"]["lockstep.groups"] == 1
    assert rep["counters"]["lockstep.chunks"] >= 1
    assert rep["values"]["lockstep.k"]["max"] == 2
    assert "lockstep.noop_set_fraction" in rep["values"]
    from abpoa_tpu.parallel import scheduler
    if scheduler.lockstep_impl(abpt) == "device":
        # all-device vmapped groups: one fused phase covers DP + fusion
        assert "align_fused" in rep["phases"]
    else:
        # split driver (round 14): DP and host fusion attributed apart
        assert "align" in rep["phases"] and "fusion" in rep["phases"]
    assert rep["counters"]["dp.cells"] > 0


def test_overhead_guard_sim2k():
    """Reporting AND tracing must be free: warm sim2k wall with telemetry
    enabled — and with the span tracer armed on top — stays within noise
    of disabled (counters are host-side dict updates, spans are two
    perf_counter calls and a ring-buffer store; never device syncs).
    Bound is deliberately loose — this guards against an accidental
    hot-loop sync, not scheduler jitter."""
    _native_or_skip()
    from abpoa_tpu import obs
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    def run_once():
        abpt = Params()
        abpt.device = "native"
        abpt.finalize()
        t0 = time.perf_counter()
        msa_from_file(Abpoa(), abpt, SIM2K, io.StringIO())
        return time.perf_counter() - t0

    run_once()  # warm: .so load, file cache
    try:
        obs.set_enabled(True)
        obs.trace_enable()
        traced = min(run_once() for _ in range(2))
        obs.trace_disable()
        on = min(run_once() for _ in range(2))
        obs.set_enabled(False)
        off = min(run_once() for _ in range(2))
    finally:
        obs.trace_disable()
        obs.set_enabled(True)
    assert on <= off * 1.25 + 0.05, (on, off)
    assert traced <= off * 1.25 + 0.05, (traced, off)


def test_disabled_report_is_empty():
    from abpoa_tpu import obs
    try:
        obs.start_run()
        obs.set_enabled(False)
        with obs.phase("align"):
            pass
        obs.count("dispatch.numpy")
        obs.observe("dp.band_width", 3)
        obs.record_dp(10, 10, 2)
    finally:
        obs.set_enabled(True)
    rep = obs.finalize_report()
    assert rep["phases"] == {} and rep["counters"] == {}
    assert rep["values"] == {} and rep["mfu"] is None


def test_mfu_model():
    from abpoa_tpu import constants as C
    from abpoa_tpu.obs.mfu import (CELL_INT_OPS, mfu_block,
                                   peak_ops_for_kind)
    from abpoa_tpu.obs.report import RunReport
    assert peak_ops_for_kind("TPU v4") == 275e12
    # both libtpu spellings of the lite chips resolve
    assert peak_ops_for_kind("TPU v5 lite") == 394e12
    assert peak_ops_for_kind("TPU v5e") == 394e12
    assert peak_ops_for_kind("TPU v6 lite") == 918e12
    assert peak_ops_for_kind("TPU v5p") == 459e12
    assert peak_ops_for_kind("TPU v9x") is None  # unknown stays None
    rep = RunReport()
    rep.phases["align_fused"] = [2.0, 1]
    rep.counters["dp.cells"] = 10_000_000
    rep.counters["dp.cell_ops"] = 10_000_000 * CELL_INT_OPS[C.CONVEX_GAP]
    # CPU device: throughput yes, MFU no
    blk = mfu_block(rep, {"platform": "cpu", "kind": ""})
    assert blk["cell_updates_per_sec"] == 5_000_000
    assert blk["mfu"] is None
    # known TPU kind: MFU appears
    blk = mfu_block(rep, {"platform": "tpu", "kind": "TPU v4"})
    assert blk["peak_ops_per_sec"] == 275e12
    assert blk["mfu"] == pytest.approx(
        10_000_000 * CELL_INT_OPS[C.CONVEX_GAP] / 2.0 / 275e12, rel=1e-4)
    # no cells recorded -> no block at all
    assert mfu_block(RunReport(), None) is None


def test_phred_vec_used_by_native_cons_matches_python():
    """The native fast path's phred column must match the Python consensus
    path byte for byte (it now shares the scalar phred)."""
    _native_or_skip()
    from abpoa_tpu.cons.consensus import phred_score, phred_score_vec
    cov = np.array([0, 1, 5, 17, 20], dtype=np.int64)
    assert phred_score_vec(cov, 20).tolist() == [
        phred_score(int(c), 20) for c in cov]


# --------------------------------------------------------------------- #
# ISSUE 6: span tracer, compile log, per-read records, perf gate         #
# --------------------------------------------------------------------- #

def test_trace_schema_golden(tmp_path):
    """Acceptance: `--trace` on sim2k emits valid Chrome trace-event JSON
    (the schema Perfetto/chrome://tracing load) whose phase-span totals
    reconcile with the RunReport phase timers to within 5%, and which
    carries per-read and per-dispatch spans nested inside the phases."""
    _native_or_skip()
    from abpoa_tpu.cli import main
    trc = str(tmp_path / "t.json")
    rpt = str(tmp_path / "r.json")
    out = str(tmp_path / "cons.fa")
    rc = main([SIM2K, "--device", "native", "-o", out,
               "--report", rpt, "--trace", trc])
    assert rc == 0
    with open(trc) as fp:
        tr = json.load(fp)
    evs = tr["traceEvents"]
    assert tr["displayTimeUnit"] == "ms" and isinstance(evs, list)
    assert {e["ph"] for e in evs} <= {"M", "X", "i"}
    spans = [e for e in evs if e["ph"] == "X"]
    for e in spans:  # the complete-event contract Perfetto parses
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert e["name"] and "pid" in e and "tid" in e
    meta = next(e for e in evs if e["name"] == "trace_meta")
    assert meta["args"]["dropped_events"] == 0
    # per-read + per-dispatch events ride along the phase spans
    cats = {e["cat"] for e in spans}
    assert {"phase", "read", "dp"} <= cats
    assert sum(1 for e in spans if e["cat"] == "read") == 20
    # span totals == phase timers (same measurement by construction)
    with open(rpt) as fp:
        rep = json.load(fp)
    tot = {}
    for e in spans:
        if e["cat"] == "phase":
            tot[e["name"]] = tot.get(e["name"], 0.0) + e["dur"] / 1e6
    assert set(tot) == set(rep["phases"])
    for name, ph in rep["phases"].items():
        assert tot[name] == pytest.approx(ph["wall_s"], rel=0.05), name


def test_trace_ring_buffer_bounds():
    """The ring buffer overwrites oldest past capacity and reports the
    drop count instead of growing without bound."""
    from abpoa_tpu.obs import trace
    t = trace.Tracer(capacity=8)
    t.enabled = True
    for i in range(20):
        t.add_span(f"s{i}", "x", float(i), 1.0)
    assert t.dropped == 12
    evs = t.events()
    assert len(evs) == 8
    assert [e[1] for e in evs] == [f"s{i}" for i in range(12, 20)]


def test_trace_disabled_records_nothing():
    from abpoa_tpu import obs
    obs.trace_disable()
    n0 = obs.tracer()._n
    with obs.span("x", "t"):
        pass
    obs.instant("y", "t")
    obs.trace.add_span("z", "t", 0.0, 1.0)
    assert obs.tracer()._n == n0


def test_compile_log_second_dispatch_is_cache_hit():
    """Satellite acceptance: a second identical-bucket dispatch of a
    jitted entry point records a cache hit; a new bucket records a new
    compile. Detection is the jit wrapper's executable cache, so this
    holds regardless of how often the bracket ran in-process."""
    import jax
    import jax.numpy as jnp
    from abpoa_tpu import obs
    from abpoa_tpu.obs import compile_log

    @jax.jit
    def f(x):
        return x * 2 + 1

    obs.start_run()
    bucket = {"N": 8, "dtype": "int32"}
    with obs.compile_watch("f", f, bucket):
        int(f(jnp.zeros(8, jnp.int32))[0])
    with obs.compile_watch("f", f, bucket):
        int(f(jnp.ones(8, jnp.int32))[0])
    # new shape -> new signature -> new compile
    with obs.compile_watch("f", f, {"N": 16, "dtype": "int32"}):
        int(f(jnp.zeros(16, jnp.int32))[0])
    recs = compile_log.run_records()
    assert [r["cache_hit"] for r in recs] == [False, True, False]
    rep = obs.finalize_report()
    comp = rep["compiles"]
    assert comp["misses"] == 2 and comp["hits"] == 1
    assert comp["count"] == 3 and comp["dropped"] == 0
    assert rep["counters"]["compile.misses"] == 2
    assert rep["counters"]["compile.hits"] == 1
    for r in recs:
        assert r["fn"] == "f" and r["wall_s"] >= 0
        assert set(r["bucket"]) == {"N", "dtype"}


def test_record_read_percentiles_and_cap():
    """Sketch-based percentiles over the per-read stream (schema v4):
    estimates stay within the declared relative error, and past READS_CAP
    only the qlen/band attribution records are dropped (and counted) —
    the percentile path keeps seeing every read."""
    # obs.report the *attribute* is a function; get the module itself
    import importlib
    R = importlib.import_module("abpoa_tpu.obs.report")
    tol = R._metrics.LogSketch.RELATIVE_ERROR
    rep = R.RunReport()
    for i in range(100):
        rep.record_read((i + 1) / 1000.0, qlen=100 + i, band_cols=50,
                        backend="native")
    blk = rep._reads_block()
    assert blk["count"] == 100 and blk["dropped"] == 0
    # nearest-rank references: p50 = 50th of 100 = 0.050 s, p99 = 0.099 s
    assert blk["wall_ms"]["p50"] == pytest.approx(50.0, rel=tol)
    assert blk["wall_ms"]["p95"] == pytest.approx(95.0, rel=tol)
    assert blk["wall_ms"]["p99"] == pytest.approx(99.0, rel=tol)
    assert blk["wall_ms"]["max"] == pytest.approx(100.0)  # min/max exact
    assert blk["sketch"]["relative_error"] == tol
    assert blk["qlen"] == {"min": 100, "max": 199, "mean": 149.5}
    rep = R.RunReport()
    old_cap = R.READS_CAP
    try:
        R.READS_CAP = 10
        for i in range(15):
            rep.record_read(0.001, 10, 5, "numpy", fallback="fused_bypass")
    finally:
        R.READS_CAP = old_cap
    blk = rep._reads_block()
    # count covers ALL reads (the sketch's honesty past the cap); the
    # raw-record drop is still visible and counted
    assert blk["count"] == 15
    assert blk["records_kept"] == 10 and blk["dropped"] == 5
    assert blk["fallbacks"] == {"fused_bypass": 15}
    assert blk["backends"] == {"numpy": 15}


def test_report_viewer(tmp_path):
    """`abpoa-tpu report FILE` renders the JSON report as a one-screen
    table carrying the phase walls, percentiles, and counters."""
    _native_or_skip()
    from abpoa_tpu.cli import main
    from abpoa_tpu.obs.report import render_report
    rpt = str(tmp_path / "r.json")
    rc = main([SIM2K, "--device", "native", "-o", str(tmp_path / "c.fa"),
               "--report", rpt])
    assert rc == 0
    with open(rpt) as fp:
        rep = json.load(fp)
    text = render_report(rep)
    assert "run report (schema v4)" in text
    for name in rep["phases"]:
        assert name in text
    assert "p50" in text and "dispatch.native" in text
    # the CLI subcommand routes to the same renderer
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["report", rpt]) == 0
    assert buf.getvalue() == text


def test_perf_gate_flips_on_regression(tmp_path):
    """Acceptance: tools/perf_gate.py exits 0 on a measurement at
    baseline and non-zero once an injected regression crosses the 15%
    reads/s threshold (deterministic --current path, no live bench)."""
    gate = os.path.join(REPO, "tools", "perf_gate.py")
    base = {"workload": "sim2k", "device": "native", "n_reads": 20,
            "wall_s": 0.1, "reads_per_sec": 200.0,
            "cell_updates_per_sec": 5.0e7}
    bpath = str(tmp_path / "base.json")
    cpath = str(tmp_path / "cur.json")
    with open(bpath, "w") as fp:
        json.dump(base, fp)
    with open(cpath, "w") as fp:
        json.dump(base, fp)

    def run(*extra):
        return subprocess.run(
            [sys.executable, gate, "--baseline", bpath, "--current", cpath,
             *extra], capture_output=True, text=True, cwd=REPO)

    ok = run()
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "PASS" in ok.stdout
    # 10% injected slowdown: inside the 15% threshold, still passes
    assert run("--inject-slowdown", "1.10").returncode == 0
    # ~20% injected slowdown: past the threshold on both metrics
    bad = run("--inject-slowdown", "1.25")
    assert bad.returncode == 1
    assert "reads_per_sec regressed" in bad.stderr
    # missing metric on either side is skipped, never a false failure
    with open(cpath, "w") as fp:
        json.dump({**base, "cell_updates_per_sec": None}, fp)
    assert run().returncode == 0


def test_device_capture_noop_without_dir(tmp_path):
    """Capture hooks never interfere when unarmed, and arm/disarm works."""
    from abpoa_tpu import obs
    with obs.device_capture("x"):
        pass  # unarmed: pure no-op
    d = str(tmp_path / "prof")
    obs.set_profile_dir(d)
    try:
        assert obs.profile_dir() == d and os.path.isdir(d)
    finally:
        obs.set_profile_dir(None)
    assert obs.profile_dir() is None
