"""Round-6 satellite fixes (ISSUE 5 / ADVICE r5 #1-#3).

1. Native int16 plane-width selection must yield to -G (inc_path_score):
   path-score accumulation is unbounded by the static score bound, so -G
   always takes the int32 core.
2. phred_score_vec must equal the scalar phred_score over the full
   coverage range (ULP divergence between numpy's and libm's pow/log10
   could flip the +0.499 truncation).
3. apg_cons_hb must not walk a dead-end graph into UB (max_out[src] == -1)
   and must seed the per-node argmax from the first edge.
"""
import numpy as np
import pytest

from abpoa_tpu import constants as C
from abpoa_tpu.params import Params


def _native_or_skip():
    from abpoa_tpu.native import load
    if load() is None:
        pytest.skip("native host core unavailable (no C++ toolchain)")
    from abpoa_tpu.native.graph import NativePOAGraph
    return NativePOAGraph


def _chain_with_decoy(NativePOAGraph, L, heavy):
    """A chain src->c0->...->c(L-1)->sink (edge w=1) where every chain node
    also feeds a shared DEAD-END decoy with weight `heavy`: each chain
    transition's -G path score is round(log(1/(heavy+1))) = -20 (the
    clamp), and no alternative route to the sink exists, so the optimal
    global alignment of the chain's own sequence scores
    L*match - 20*(L-1)."""
    g = NativePOAGraph()
    ids = [g.add_node(0) for _ in range(L)]
    dec = g.add_node(0)
    g.add_edge(C.SRC_NODE_ID, ids[0], True, 1, False, False, 0, 0)
    for i in range(L - 1):
        g.add_edge(ids[i], ids[i + 1], True, 1, False, False, 0, 0)
        g.add_edge(ids[i], dec, True, heavy, False, False, 0, 0)
    g.add_edge(ids[-1], C.SINK_NODE_ID, True, 1, False, False, 0, 0)
    return g


def test_native_g_mode_takes_int32_core():
    """Regression (ADVICE r5 #1): with -G at a config whose static score
    bound fits int16 (bound = qlen*max_mat = 4000 <= ~31k limit), the
    accumulated -20-per-transition path scores sink the optimum to -35980,
    far below INT16_MIN. The pre-fix int16 core wrapped and failed its
    backtrack (rc=-1); the -G-aware width selection must return the exact
    analytic optimum."""
    NativePOAGraph = _native_or_skip()
    from abpoa_tpu.align import align_sequence_to_graph
    L = 2000
    g = _chain_with_decoy(NativePOAGraph, L, heavy=485165195)  # ~e^20
    abpt = Params()
    abpt.device = "native"
    abpt.inc_path_score = True
    abpt.finalize()
    res = align_sequence_to_graph(g, abpt, np.zeros(L, dtype=np.uint8))
    assert res.best_score == abpt.match * L - 20 * (L - 1)  # == -35980


def test_phred_score_vec_matches_scalar_full_range():
    """ADVICE r5 #2: vec == scalar over the whole 0..n_seq coverage range,
    for a spread of cluster sizes."""
    from abpoa_tpu.cons.consensus import phred_score, phred_score_vec
    for n_seq in (1, 2, 3, 7, 33, 200, 1000):
        cov = np.arange(n_seq + 1, dtype=np.int64)
        vec = phred_score_vec(cov, n_seq)
        scal = np.array([phred_score(int(c), n_seq) for c in cov],
                        dtype=np.int64)
        assert (vec == scal).all(), f"divergence at n_seq={n_seq}"


def test_phred_score_vec_rejects_over_coverage():
    from abpoa_tpu.cons.consensus import phred_score_vec
    with pytest.raises(ValueError):
        phred_score_vec(np.array([5]), 4)
    assert phred_score_vec(np.array([], dtype=np.int64), 4).size == 0


def test_native_cons_hb_dead_end_graph():
    """ADVICE r5 #3: a graph whose heaviest branch dies before the sink
    leaves the reverse BFS unable to reach the source; apg_cons_hb must
    return an empty consensus instead of walking max_out[src] == -1."""
    NativePOAGraph = _native_or_skip()
    g = NativePOAGraph()
    a = g.add_node(0)
    b = g.add_node(1)
    d = g.add_node(2)  # dead end: no out edges
    g.add_edge(C.SRC_NODE_ID, a, True, 5, False, False, 0, 0)
    g.add_edge(a, d, True, 5, False, False, 0, 0)
    g.add_edge(C.SRC_NODE_ID, b, True, 1, False, False, 0, 0)
    g.add_edge(b, C.SINK_NODE_ID, True, 1, False, False, 0, 0)
    ids, bases, covs = g.consensus_hb()
    assert len(ids) == len(bases) == len(covs) == 0


def test_native_cons_hb_normal_graph_unchanged():
    """The first-edge argmax seeding must keep the reference tie behavior
    on a healthy graph: heaviest chain src->x->y->sink wins."""
    NativePOAGraph = _native_or_skip()
    g = NativePOAGraph()
    x = g.add_node(1)
    y = g.add_node(2)
    z = g.add_node(3)
    g.add_edge(C.SRC_NODE_ID, x, True, 3, False, False, 0, 0)
    g.add_edge(x, y, True, 3, False, False, 0, 0)
    g.add_edge(y, C.SINK_NODE_ID, True, 3, False, False, 0, 0)
    g.add_edge(C.SRC_NODE_ID, z, True, 1, False, False, 0, 0)
    g.add_edge(z, C.SINK_NODE_ID, True, 1, False, False, 0, 0)
    ids, bases, covs = g.consensus_hb()
    assert list(ids) == [x, y]
    assert list(bases) == [1, 2]
