"""Cross-mode regression: run the full pipeline in-process on shipped data and
compare against frozen outputs produced by the reference binary (AVX2).

These goldens were captured once with the reference build; they freeze the
byte-exact contract for align modes x gap regimes x output modes.
"""
import io
import os

import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def run_cli(args):
    out = io.StringIO()
    from abpoa_tpu.cli import build_parser, args_to_params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    ns = build_parser().parse_args(args)
    abpt = args_to_params(ns).finalize()
    ab = Abpoa()
    msa_from_file(ab, abpt, ns.input, out)
    return out.getvalue()


CONFIGS = [
    ("seq.fa", ["-m1"], "seq_m1.txt"),
    ("seq.fa", ["-m2"], "seq_m2.txt"),
    ("seq.fa", ["-O", "4"], "seq_affine.txt"),
    ("seq.fa", ["-O", "0"], "seq_linear.txt"),
    ("seq.fa", ["-b", "-1"], "seq_noband.txt"),
    ("seq.fa", ["-r2"], "seq_r2.txt"),
    ("seq.fa", ["-r4"], "seq_r4.txt"),
    ("seq.fa", ["-r5"], "seq_r5.txt"),
    ("seq.fa", ["-S", "-p"], "seq_Sp.txt"),
    ("heter.fa", ["-d2", "-r2"], "heter_d2r2.txt"),
    ("3alleles.fa", ["-d3"], "3alleles_d3.txt"),
    ("heter.fq", ["-d2", "-Q"], "heterq_d2Q.txt"),
]


@pytest.mark.parametrize("data,args,golden", CONFIGS, ids=[c[2] for c in CONFIGS])
def test_config(data, args, golden):
    path = os.path.join(GOLDEN_DIR, golden)
    if not os.path.exists(path):
        pytest.skip(f"golden {golden} not captured")
    got = run_cli([os.path.join(DATA_DIR, data)] + args)
    with open(path) as fp:
        assert got == fp.read()
