"""Property tests: the JAX kernel must agree with the NumPy oracle on random
progressive-POA runs across {align mode} x {gap regime} x {banding}, and the
host engines must agree with each other up to the documented penalty bounds.

This is the moral equivalent of the reference's __SIMD_DEBUG__ scalar kernel
used as an oracle for the vector kernel (SURVEY.md §4).
"""
import io
import os

import numpy as np
import pytest

from conftest import DATA_DIR

from abpoa_tpu import constants as C
from abpoa_tpu.graph import POAGraph
from abpoa_tpu.params import Params
from abpoa_tpu.pipeline import Abpoa, poa


def _random_reads(rng, n_reads, length, err=0.12):
    ref = rng.integers(0, 4, length)
    reads = []
    for _ in range(n_reads):
        read = []
        for b in ref:
            x = rng.random()
            if x < err * 0.4:
                read.append((b + rng.integers(1, 4)) % 4)
            elif x < err * 0.7:
                read.append(b)
                read.append(rng.integers(0, 4))
            elif x < err:
                pass
            else:
                read.append(b)
        reads.append(np.array(read, dtype=np.uint8))
    return reads


def _run(abpt, reads):
    ab = Abpoa()
    ab.graph = POAGraph()
    for r in reads:
        ab.names.append("")
        ab.comments.append("")
        ab.quals.append(None)
        ab.seqs.append("x" * len(r))
        ab.is_rc.append(False)
    weights = [np.ones(len(r), dtype=np.int64) for r in reads]
    poa(ab, abpt, reads, weights, 0)
    from abpoa_tpu.cons.consensus import generate_consensus
    abc = generate_consensus(ab.graph, abpt, len(reads))
    return abc.cons_base


CASES = [
    (C.GLOBAL_MODE, C.CONVEX_GAP, 10),
    (C.GLOBAL_MODE, C.AFFINE_GAP, 10),
    (C.GLOBAL_MODE, C.LINEAR_GAP, 10),
    (C.GLOBAL_MODE, C.CONVEX_GAP, -1),
    (C.LOCAL_MODE, C.CONVEX_GAP, 10),
    (C.EXTEND_MODE, C.CONVEX_GAP, 10),
    (C.EXTEND_MODE, C.AFFINE_GAP, -1),
]


@pytest.mark.parametrize("mode,gap,wb", CASES,
                         ids=[f"m{m}-g{g}-b{b}" for m, g, b in CASES])
def test_jax_matches_oracle(mode, gap, wb):
    rng = np.random.default_rng(mode * 100 + gap * 10 + wb + 2)
    reads = _random_reads(rng, 6, 150)

    def mk(device):
        abpt = Params()
        abpt.align_mode = mode
        abpt.wb = wb
        if gap == C.LINEAR_GAP:
            abpt.gap_open1 = abpt.gap_open2 = 0
        elif gap == C.AFFINE_GAP:
            abpt.gap_open2 = 0
        abpt.device = device
        return abpt.finalize()

    cons_np = _run(mk("numpy"), reads)
    cons_jx = _run(mk("jax"), reads)
    assert cons_np == cons_jx


EXTRA_CASES = [
    # extend + Z-drop (abpoa_align_simd.c:1076-1090), banded and unbanded
    (C.EXTEND_MODE, C.CONVEX_GAP, 10, {"zdrop": 20}),
    (C.EXTEND_MODE, C.CONVEX_GAP, -1, {"zdrop": 15}),
    (C.EXTEND_MODE, C.AFFINE_GAP, 10, {"zdrop": 25}),
    # -G log-scaled path scores (abpoa_graph.c:429-437)
    (C.GLOBAL_MODE, C.CONVEX_GAP, 10, {"inc_path_score": True}),
    (C.GLOBAL_MODE, C.LINEAR_GAP, 10, {"inc_path_score": True}),
    (C.EXTEND_MODE, C.CONVEX_GAP, 10, {"inc_path_score": True, "zdrop": 20}),
]


@pytest.mark.parametrize("mode,gap,wb,extra", EXTRA_CASES,
                         ids=[f"m{m}-g{g}-b{b}-" + "-".join(e)
                              for m, g, b, e in EXTRA_CASES])
def test_jax_matches_oracle_zdrop_pathscore(mode, gap, wb, extra):
    """The device kernel must cover -G and extend+Z-drop natively (no oracle
    fallback; VERDICT round-1 item 6)."""
    rng = np.random.default_rng(mode * 100 + gap * 10 + wb + 7)
    reads = _random_reads(rng, 6, 150)

    def mk(device):
        abpt = Params()
        abpt.align_mode = mode
        abpt.wb = wb
        if gap == C.LINEAR_GAP:
            abpt.gap_open1 = abpt.gap_open2 = 0
        elif gap == C.AFFINE_GAP:
            abpt.gap_open2 = 0
        for k, v in extra.items():
            setattr(abpt, k, v)
        abpt.device = device
        return abpt.finalize()

    cons_np = _run(mk("numpy"), reads)
    import abpoa_tpu.align.oracle as oracle_mod
    calls = {"n": 0}
    orig = oracle_mod.align_sequence_to_subgraph_numpy
    oracle_mod.align_sequence_to_subgraph_numpy = (
        lambda *a, **k: (calls.__setitem__("n", calls["n"] + 1), orig(*a, **k))[1])
    try:
        cons_jx = _run(mk("jax"), reads)
    finally:
        oracle_mod.align_sequence_to_subgraph_numpy = orig
    assert cons_np == cons_jx
    assert calls["n"] == 0, "jax path silently fell back to the oracle"


# --------------------------------------------------------------------- #
# the -E gap-extension contract (ROADMAP item 5, PERF.md round 10):     #
# parity through 63, explicit rejection from 64 — the regime where the  #
# reference binary crashes ("Error in lg_backtrack") and the in-tree    #
# engines were measured to diverge                                      #
# --------------------------------------------------------------------- #

def _msa_output(device: str, ext: int, records) -> str:
    from abpoa_tpu.pipeline import msa
    abpt = Params()
    abpt.gap_open1 = abpt.gap_open2 = 0   # linear gaps: -O 0 -E ext
    abpt.gap_ext1, abpt.gap_ext2 = ext, 0
    abpt.device = device
    abpt.finalize()
    buf = io.StringIO()
    msa(Abpoa(), abpt, records, buf)
    return buf.getvalue()


@pytest.mark.parametrize("ext", [40, 56, 63])
def test_native_oracle_parity_up_to_gap_ext_bound(ext):
    """The historical round-5 divergence witness (seq.fa, -O 0): native
    and the numpy oracle must agree byte-for-byte right up to the
    documented bound (the measured boundary is exactly 64)."""
    from abpoa_tpu.native import load
    if load() is None:
        pytest.skip("native host core unavailable (no C++ toolchain)")
    from abpoa_tpu.io.fastx import read_fastx
    records = read_fastx(os.path.join(DATA_DIR, "seq.fa"))
    assert _msa_output("numpy", ext, records) == \
        _msa_output("native", ext, records)


def test_gap_ext_at_bound_rejected():
    """-E>=64 is a validation error (clamp-or-error decision: ERROR —
    clamping would silently change scoring semantics), for either
    extension and from the CLI as a structured one-liner, never a
    traceback."""
    abpt = Params()
    abpt.gap_open1 = abpt.gap_open2 = 0
    abpt.gap_ext1 = C.MAX_GAP_EXT
    abpt.gap_ext2 = 0
    with pytest.raises(ValueError, match="supported range"):
        abpt.finalize()
    # the convex second-level extension is bounded identically
    abpt2 = Params()
    abpt2.gap_ext2 = C.MAX_GAP_EXT + 8
    with pytest.raises(ValueError, match="supported range"):
        abpt2.finalize()
    # one below the bound finalizes fine
    abpt3 = Params()
    abpt3.gap_open1 = abpt3.gap_open2 = 0
    abpt3.gap_ext1, abpt3.gap_ext2 = C.MAX_GAP_EXT - 1, 0
    abpt3.finalize()


def test_gap_ext_bound_cli_structured_error(capsys):
    from abpoa_tpu.cli import main
    rc = main([os.path.join(DATA_DIR, "seq.fa"), "-O", "0", "-E", "64"])
    assert rc == 1
    err = capsys.readouterr().err
    assert err.startswith("Error:") and "supported range" in err
    assert "Traceback" not in err
