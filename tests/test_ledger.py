"""Performance-trajectory ledger + round/shard timeline tests (round 20).

- schema golden: `ledger.make_record` pins schema v1's exact key set and
  lints clean; `lint_record` catches shape drift
- append/rotation: O_APPEND JSONL round-trips, `append_unique` is
  idempotent, rotation keeps exactly ONE prior generation and
  `read_window` spans the boundary
- drift gate: pure `drift_check` verdicts (regression flagged, short
  history vacuous), and the `abpoa-tpu perf --gate` subprocess flips
  rc 0 -> 1 under --inject-slowdown (the self-test contract every gate
  carries)
- backfill: tools/ledger_backfill.py imports >= 15 records from the
  repo's BENCH_*/MULTICHIP_*/baseline files and re-runs as a no-op
- round ring: bounded overwrite with a dropped() count, per-shard wall
  estimates/skew/straggler math, skew_summary for `why`
- reconcile: a real lockstep run's round-timeline dp walls sum to within
  5% of the `dp` trace-span totals (they bracket the same region by
  construction)
- `top`: the shard-skew row renders from published skew gauges
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def ledger_dir(tmp_path, monkeypatch):
    d = tmp_path / "ledger"
    monkeypatch.setenv("ABPOA_TPU_LEDGER_DIR", str(d))
    monkeypatch.delenv("ABPOA_TPU_LEDGER", raising=False)
    monkeypatch.delenv("ABPOA_TPU_LEDGER_MAX_MB", raising=False)
    return d


# ------------------------------------------------------------- schema


def test_schema_golden_record(ledger_dir):
    """Schema v1's key set is pinned: adding/renaming a field must be a
    conscious schema_version bump, not drift."""
    from abpoa_tpu.obs import ledger
    rec = ledger.make_record(
        "perf_gate", workload="sim2k", device="native", route="serial",
        rung={"K": 4}, reads_per_sec=359.7, cell_updates_per_sec=9.8e7,
        mfu=0.12, occupancy=0.9,
        read_wall_ms={"p50": 2.5, "p95": 5.5, "p99": 5.5},
        compile_misses=0, verdict="pass")
    assert set(rec) == set(ledger.REQUIRED_KEYS)
    assert rec["schema_version"] == ledger.LEDGER_SCHEMA_VERSION
    assert rec["host"]["cpus"] >= 1
    assert rec["rung"] == {"K": 4}
    assert len(rec["key"]) == 16
    assert ledger.lint_record(rec) == []
    # extra is the only optional key, carried verbatim
    rec2 = ledger.make_record("bench", extra={"vs_baseline": 3.0})
    assert set(rec2) == set(ledger.REQUIRED_KEYS) | {"extra"}
    assert ledger.lint_record(rec2) == []


def test_lint_record_catches_drift():
    from abpoa_tpu.obs import ledger
    rec = ledger.make_record("bench", workload="sim2k")
    assert ledger.lint_record(rec) == []
    bad = dict(rec, schema_version=99, rung="K=4", reads_per_sec="fast")
    bad.pop("verdict")
    problems = "\n".join(ledger.lint_record(bad))
    assert "schema_version" in problems
    assert "rung is not a dict" in problems
    assert "reads_per_sec is not numeric" in problems
    assert "missing key 'verdict'" in problems


# --------------------------------------------------- append + rotation


def test_append_roundtrip_and_unique(ledger_dir):
    from abpoa_tpu.obs import ledger
    rec = ledger.make_record("bench", workload="sim2k", reads_per_sec=10.0)
    path = ledger.append_record(rec)
    assert path == str(ledger_dir / "PERF_LEDGER.jsonl")
    assert ledger.append_unique(rec) is None          # same key: skipped
    win = ledger.read_window(0)
    assert len(win) == 1 and win[0]["key"] == rec["key"]
    # append_and_verify (the smokes' self-check) is clean on a good record
    rec2 = ledger.make_record("serve_smoke", workload="soak", verdict="pass")
    assert ledger.append_and_verify(rec2) == []
    # and silent when the ledger is operator-disabled
    os.environ["ABPOA_TPU_LEDGER"] = "0"
    try:
        assert ledger.append_and_verify(rec2) == []
        assert ledger.append_record(rec2) is None
    finally:
        del os.environ["ABPOA_TPU_LEDGER"]


def test_rotation_keeps_one_generation(ledger_dir, monkeypatch):
    """Past the size cap the live file rotates to `.1`; a second rotation
    REPLACES `.1` (one prior generation, never `.2`), and read_window
    spans the boundary."""
    monkeypatch.setenv("ABPOA_TPU_LEDGER_MAX_MB", "0.002")   # 2 kB cap
    from abpoa_tpu.obs import ledger
    for i in range(40):  # ~500 B/record -> several rotations
        ledger.append_record(ledger.make_record(
            "bench", workload="sim2k", reads_per_sec=float(i),
            key=f"rot{i:02d}"))
    path = ledger.ledger_path()
    assert os.path.exists(path + ".1")
    assert not os.path.exists(path + ".2")
    win = ledger.read_window(0)
    keys = [r["key"] for r in win]
    assert keys == sorted(keys)                  # oldest-first, in order
    assert keys[-1] == "rot39"                   # newest survived
    assert len(win) < 40                         # old generations dropped
    # the window spans the rotation boundary: some records live in .1
    with open(path) as fp:
        live = fp.read().count("\n")
    assert len(win) > live


# ----------------------------------------------------------- drift gate


def _mk(ledger, rps, i):
    return ledger.make_record("g", workload="w", reads_per_sec=rps,
                              key=f"d{i:02d}")


def test_drift_check_flags_regression(ledger_dir):
    from abpoa_tpu.obs import ledger
    window = [_mk(ledger, 100.0, i) for i in range(5)]
    window.append(_mk(ledger, 50.0, 9))         # 0.5x median: below 0.6
    verdicts = ledger.drift_check(window, metrics=("reads_per_sec",))
    assert [v["ok"] for v in verdicts] == [False]
    assert verdicts[0]["median"] == 100.0
    # same history, healthy current: passes
    ok = ledger.drift_check(window[:-1] + [_mk(ledger, 95.0, 9)],
                            metrics=("reads_per_sec",))
    assert ok[0]["ok"]
    # short history is vacuous
    short = ledger.drift_check(window[:3], metrics=("reads_per_sec",))
    assert short[0]["ok"] and short[0]["note"] == "history<min"


def test_perf_gate_subprocess_flip(ledger_dir):
    """The CI contract, end to end: `abpoa-tpu perf --gate` exits 0 on a
    healthy trajectory and 1 under --inject-slowdown; an empty ledger is
    rc 1 (the gate must not vacuously pass with no history)."""
    from abpoa_tpu.obs import ledger
    env = dict(os.environ, ABPOA_TPU_LEDGER_DIR=str(ledger_dir),
               JAX_PLATFORMS="cpu", ABPOA_TPU_SKIP_PROBE="1")

    def gate(*extra):
        return subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", "perf", "--gate",
             *extra],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)

    r = gate()
    assert r.returncode == 1 and "empty" in r.stderr
    for i in range(5):
        ledger.append_record(_mk(ledger, 100.0 + i, i))
    r = gate()
    assert r.returncode == 0, r.stderr
    assert "[perf-drift] PASS" in r.stderr
    r = gate("--inject-slowdown", "10")
    assert r.returncode == 1, r.stderr
    assert "DRIFT" in r.stderr


def test_backfill_importer(tmp_path):
    """>= 15 records from the repo's historical files, idempotent, and
    the backfilled trajectory passes the drift gate (acceptance: `perf
    --gate` green on backfill + current)."""
    d = str(tmp_path / "bf")
    env = dict(os.environ, JAX_PLATFORMS="cpu", ABPOA_TPU_SKIP_PROBE="1")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ledger_backfill.py"),
         "--ledger-dir", d],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    with open(os.path.join(d, "PERF_LEDGER.jsonl")) as fp:
        recs = [json.loads(line) for line in fp]
    assert len(recs) >= 15
    from abpoa_tpu.obs import ledger
    assert all(ledger.lint_record(rec) == [] for rec in recs)
    sources = {rec["source"] for rec in recs}
    assert {"bench", "shard_gate", "multichip", "abpoa_ref",
            "perf_gate"} <= sources
    # re-run: no duplicates
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ledger_backfill.py"),
         "--ledger-dir", d],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert "0 imported" in r2.stderr, r2.stderr
    env2 = dict(env, ABPOA_TPU_LEDGER_DIR=d)
    r3 = subprocess.run(
        [sys.executable, "-m", "abpoa_tpu.cli", "perf", "--gate"],
        cwd=REPO, env=env2, capture_output=True, text=True, timeout=120)
    assert r3.returncode == 0, r3.stderr


# ------------------------------------------------------- round timeline


def test_round_ring_bounded_drop():
    from abpoa_tpu.obs import rounds
    rounds.reset(capacity=16)
    try:
        for i in range(40):
            rounds.record_round("lockstep", lanes=4, k_cap=4,
                                wall_s=0.001 * (i + 1))
        ring = rounds.ring()
        assert ring.total == 40
        assert rounds.dropped() == 24
        samples = ring.samples()
        assert len(samples) == 16
        # oldest-first, newest retained
        walls = [s.wall_s for s in samples]
        assert walls == sorted(walls)
        assert walls[-1] == pytest.approx(0.040)
    finally:
        rounds.reset()


def test_shard_wall_estimates_and_skew():
    from abpoa_tpu.obs import rounds
    rounds.reset()
    try:
        rounds.begin_round()
        rounds.note_dispatch(0.08, shard_live=[4, 2, 0, 1])
        s = rounds.record_round("sharded", lanes=7, k_cap=32,
                                wall_s=0.1, mesh=4)
        walls = rounds.shard_wall_estimates(s)
        # straggler (max-live shard) carries the measured dispatch wall
        assert walls[0] == pytest.approx(0.08)
        assert walls[1] == pytest.approx(0.04)
        assert walls[2] == 0.0
        ratio, straggler = rounds.skew_of(s)
        assert straggler == 0
        assert ratio == pytest.approx(4.0)       # 4 live vs min-live 1
        summ = rounds.skew_summary()
        assert summ["slowest_shard"] == 0
        assert summ["shard_skew"] == pytest.approx(4.0)
        assert summ["shard_live"] == [4, 2, 0, 1]
    finally:
        rounds.reset()


def test_rounds_reconcile_with_dp_spans():
    """The round timeline's dispatch walls and the `dp` trace spans
    bracket the same code region, so their totals agree within 5% on a
    real lockstep run."""
    from abpoa_tpu.obs import rounds, trace
    from abpoa_tpu.parallel.lockstep import progressive_poa_split_batch
    from abpoa_tpu.params import Params
    rng = np.random.default_rng(2000)
    sets, wsets = [], []
    for n in (3, 4):
        L = int(rng.integers(60, 120))
        ref = rng.integers(0, 4, L).astype(np.uint8)
        reads = []
        for _ in range(n):
            r = ref.copy()
            posn = rng.integers(0, L, max(1, L // 10))
            r[posn] = rng.integers(0, 4, len(posn))
            reads.append(r)
        sets.append(reads)
        wsets.append([np.ones(len(r), dtype=np.int64) for r in reads])
    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"
    abpt.finalize()
    trace.enable()
    rounds.reset()
    try:
        outs = progressive_poa_split_batch(sets, wsets, abpt)
        assert all(o is not None for o in outs)
        ring_total = rounds.dp_wall_total()
        span_total = trace.span_totals("dp").get("dp_chunk", 0.0)
        assert ring_total > 0 and span_total > 0
        assert ring_total == pytest.approx(span_total, rel=0.05)
        # every round landed a sample with live lanes
        snap = rounds.snapshot()
        assert snap and all(s["lanes"] >= 1 for s in snap)
        assert {s["route"] for s in snap} == {"lockstep"}
    finally:
        trace.disable()
        rounds.reset()


def test_top_renders_shard_skew_row():
    """`top` shows the shard-skew row (max/min shard wall + straggler)
    once the skew gauges are published — the virtual 8-mesh surface."""
    from abpoa_tpu.obs import metrics as M
    from abpoa_tpu.obs.top import render_frame
    M.reset_registry()
    try:
        M.publish_counter("scheduler.sharded.mesh", 1)
        M.publish_mesh(8, "cpu")
        M.publish_round("sharded", 0.125, 14, 64)
        M.publish_shard_skew(2.5, 3, {i: 0.01 * (i + 1) for i in range(8)})
        samples, types = M.parse_exposition(M.registry().render())
        frame = render_frame(samples, types, "test.prom", 0.0)
        assert "skew 2.50x" in frame
        assert "straggler shard 3" in frame
        assert "shard 7" in frame                # max-wall shard named
    finally:
        M.reset_registry()
