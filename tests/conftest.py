import os
import sys

# Multi-chip sharding tests run on a virtual 8-device CPU mesh; force this
# before jax initializes (the environment may preset JAX_PLATFORMS to a real
# accelerator). Tests that need the real TPU must spawn a subprocess.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# the suite runs CPU-only: skip the out-of-process accelerator liveness probe
# (tests/test_probe.py exercises the probe itself and clears this)
os.environ["ABPOA_TPU_SKIP_PROBE"] = "1"
# never read/write the cross-process probe verdict cache from tests: the
# wedge-simulation children would poison it for real runs on this host (and
# a stale real verdict would defeat the simulation)
os.environ["ABPOA_TPU_PROBE_CACHE_TTL"] = "0"
# keep the suite's hundreds of CLI runs out of the user's cross-run report
# archive (~/.cache/abpoa_tpu/reports); archive tests opt back in with an
# explicit ABPOA_TPU_ARCHIVE_DIR + ABPOA_TPU_ARCHIVE=1 (tests/test_metrics.py)
os.environ.setdefault("ABPOA_TPU_ARCHIVE", "0")
# the suite's many multi-set run_batch calls stay on the in-process serial
# path: the process pool (parallel/pool.py) spawns interpreter children per
# worker, which the 870s tier-1 budget cannot afford as a side effect.
# Pool tests opt back in with an explicit Params.workers / --workers N.
os.environ.setdefault("ABPOA_TPU_WORKERS", "1")
# pool-worker flight-recorder dumps (obs/flight.py) stay out of the user's
# ~/.cache/abpoa_tpu/flight; tests that assert on dumps pin their own dir.
# Removed at interpreter exit so repeated suite runs don't accumulate /tmp
# directories.
if "ABPOA_TPU_FLIGHT_DIR" not in os.environ:
    import atexit as _atexit  # noqa: E402
    import shutil as _shutil  # noqa: E402
    import tempfile as _tempfile  # noqa: E402
    _flight_tmp = _tempfile.mkdtemp(prefix="abpoa_flight_test_")
    os.environ["ABPOA_TPU_FLIGHT_DIR"] = _flight_tmp
    _atexit.register(_shutil.rmtree, _flight_tmp, True)
# persistent compilation cache: the device-path tests are dominated by XLA
# compile time (minutes per pallas-interpret variant); cache across runs and
# across the subprocess-isolated children, which inherit this env
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))


def _drop_accelerator_plugins():
    """Deregister non-CPU PJRT plugins (e.g. the axon TPU tunnel) so CPU-only
    tests never open a device connection."""
    try:
        import jax
        # the site hook may have read JAX_PLATFORMS before we forced "cpu"
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


_drop_accelerator_plugins()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")


# ---- background accelerator probe --------------------------------------- #
# The compiled-on-chip tests need to know whether a real accelerator
# answers. Probing lazily at test time used to cost a full 90 s timeout of
# dead wall time per cold suite on a wedged tunnel. Instead the probe child
# starts at COLLECTION time — and only when an on-chip test was actually
# collected — so by the time those tests ask (minutes into the run) the
# answer is ready at zero added wall-clock, and selections with no on-chip
# test never spawn it.
#
# State lives on `sys` (not this module): pytest loads this file as
# top-level `conftest` while test files import `tests.conftest` — TWO
# module instances. A module-global here would spawn two probe children,
# and on a real TPU host the second child's backend init fails against the
# first's exclusive chip lock, mis-answering "no accelerator".
import atexit
import subprocess
import time

_PROBE_DEADLINE_S = 90
_PROBE_KEY = "_abpoa_tpu_probe_state"


def _start_accelerator_probe():
    if getattr(sys, _PROBE_KEY, None) is not None:
        return
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the real platform win in the child
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); "
             "print('acc' if any(x.platform!='cpu' for x in d) else 'cpu')"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, start_new_session=True)
        setattr(sys, _PROBE_KEY,
                {"proc": proc, "started": time.time(), "answer": None})
    except Exception:
        setattr(sys, _PROBE_KEY, {"proc": None, "started": 0.0,
                                  "answer": False})


def _kill_probe():
    st = getattr(sys, _PROBE_KEY, None)
    if st and st["proc"] is not None and st["proc"].poll() is None:
        try:
            st["proc"].kill()
        except Exception:
            pass


def accelerator_reachable() -> bool:
    """True iff the probe child saw a non-CPU platform. Blocks only for
    whatever remains of the 90 s budget that started at collection (or
    starts the probe now if no on-chip test was collected this run)."""
    _start_accelerator_probe()  # no-op when already started
    st = getattr(sys, _PROBE_KEY)
    if st["answer"] is not None:
        return st["answer"]
    remaining = max(1.0, _PROBE_DEADLINE_S - (time.time() - st["started"]))
    try:
        out, _ = st["proc"].communicate(timeout=remaining)
        st["answer"] = st["proc"].returncode == 0 and "acc" in out
    except subprocess.TimeoutExpired:
        _kill_probe()
        st["answer"] = False
    return st["answer"]


def pytest_collection_modifyitems(config, items):
    if any("compiled_on_chip" in item.nodeid for item in items):
        _start_accelerator_probe()


atexit.register(_kill_probe)
