import os
import sys

# Multi-chip sharding tests run on a virtual 8-device CPU mesh; set this before
# jax initializes. Tests that need the real TPU must spawn a subprocess.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")
