import os
import sys

# Multi-chip sharding tests run on a virtual 8-device CPU mesh; force this
# before jax initializes (the environment may preset JAX_PLATFORMS to a real
# accelerator). Tests that need the real TPU must spawn a subprocess.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# the suite runs CPU-only: skip the out-of-process accelerator liveness probe
# (tests/test_probe.py exercises the probe itself and clears this)
os.environ["ABPOA_TPU_SKIP_PROBE"] = "1"
# never read/write the cross-process probe verdict cache from tests: the
# wedge-simulation children would poison it for real runs on this host (and
# a stale real verdict would defeat the simulation)
os.environ["ABPOA_TPU_PROBE_CACHE_TTL"] = "0"
# persistent compilation cache: the device-path tests are dominated by XLA
# compile time (minutes per pallas-interpret variant); cache across runs and
# across the subprocess-isolated children, which inherit this env
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))


def _drop_accelerator_plugins():
    """Deregister non-CPU PJRT plugins (e.g. the axon TPU tunnel) so CPU-only
    tests never open a device connection."""
    try:
        import jax
        # the site hook may have read JAX_PLATFORMS before we forced "cpu"
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


_drop_accelerator_plugins()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")
