"""Packaging: the wheel must carry everything an installed user needs
(VERDICT round-1 item: no wheel/install test anywhere). No packages are
installed — the wheel is built offline and inspected."""
import os
import subprocess
import sys
import zipfile

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(os.environ.get("ABPOA_SKIP_WHEEL") == "1",
                    reason="wheel build disabled")
def test_wheel_builds_and_is_complete(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", ROOT, "--no-deps",
         "--no-build-isolation", "-w", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-800:]
    wheels = [f for f in os.listdir(tmp_path) if f.endswith(".whl")]
    assert len(wheels) == 1
    names = zipfile.ZipFile(tmp_path / wheels[0]).namelist()

    required = [
        "abpoa_tpu/cli.py",
        "abpoa_tpu/pyapi.py",
        "abpoa_tpu/pipeline.py",
        "abpoa_tpu/align/fused_loop.py",
        "abpoa_tpu/align/pallas_fused.py",
        # the C++ source must ship: the native backend builds on demand
        # per host (abpoa_tpu/native/__init__.py)
        "abpoa_tpu/native/host_core.cpp",
    ]
    for path in required:
        assert any(n.endswith(path) for n in names), f"wheel missing {path}"
    # no build artifacts leak into the wheel
    assert not any(n.endswith(".so") for n in names)
