"""`abpoa-tpu serve` tests (ISSUE 12): admission control, per-request
deadlines, poisoned-set isolation, endpoint contracts, graceful drain,
loadgen, and the `top` serve panel.

In-process servers run on the numpy host backend (no jax import, fast
startup); the SIGTERM drain test uses a real subprocess because exit
status and signal handling ARE the contract under test."""
import io
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from conftest import DATA_DIR

TEST_FA = os.path.join(DATA_DIR, "test.fa")
POISON_FQ = b"@truncated\nACGTACGT\n+\nIII\n"   # qual len != seq len
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    rz.inject.reset()
    rz.breaker().reset()
    yield
    rz.inject.reset()
    rz.breaker().reset()
    obs.start_run()


def _params(device="numpy"):
    from abpoa_tpu.params import Params
    abpt = Params()
    abpt.device = device
    return abpt


def _oracle_bytes(path=TEST_FA):
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.pipeline import Abpoa, msa
    buf = io.StringIO()
    msa(Abpoa(), _params().finalize(), read_fastx(path), buf)
    return buf.getvalue().encode()


def _start_server(**kw):
    from abpoa_tpu.serve import AlignServer
    srv = AlignServer(_params(), port=0, **kw)
    srv.start(warm="off")
    return srv


def _post(base, body, headers=None, timeout=30):
    req = urllib.request.Request(base + "/align", data=body, method="POST",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _get_json(base, path, timeout=10):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# --------------------------------------------------------------------- #
# admission unit tests                                                   #
# --------------------------------------------------------------------- #

def _job(rung=128, est=1000, eligible=True, deadline=30.0):
    from abpoa_tpu.serve.admission import Job
    return Job(records=[], rung=rung, est_bytes=est, eligible=eligible,
               deadline_s=deadline)


def test_admission_depth_bound():
    from abpoa_tpu.serve.admission import AdmissionController
    adm = AdmissionController(_params(), max_depth=2, budget_bytes=None)
    assert adm.try_admit(_job())[0]
    assert adm.try_admit(_job())[0]
    ok, reason, retry = adm.try_admit(_job())
    assert not ok and reason == "queue_full" and retry >= 1.0
    # draining refuses everything
    adm.close_intake()
    assert adm.try_admit(_job())[1] == "draining"


def test_admission_memory_bound_never_starves_solo_request():
    from abpoa_tpu.serve.admission import AdmissionController
    adm = AdmissionController(_params(), max_depth=10, budget_bytes=1000)
    # a single over-budget request is ALWAYS admissible on an empty
    # system (dispatch-time admission chunks/demotes it); the byte gate
    # bounds concurrency only
    big = _job(est=5000)
    assert adm.try_admit(big)[0]
    ok, reason, _ = adm.try_admit(_job(est=10))
    assert not ok and reason == "memory"
    group = adm.next_group()
    assert group == [big]
    adm.mark_done(big)
    # after release the small one fits
    assert adm.try_admit(_job(est=10))[0]


def test_admission_coalesces_same_rung_only():
    from abpoa_tpu.serve.admission import AdmissionController
    adm = AdmissionController(_params(), max_depth=10, budget_bytes=None)
    a, b, c, d = (_job(rung=128), _job(rung=256), _job(rung=128),
                  _job(rung=128, eligible=False))
    for j in (a, b, c, d):
        assert adm.try_admit(j)[0]
    group = adm.next_group(max_k=4, coalesce=True)
    # head rung 128 packs the later 128 job, skips the 256 and the
    # ineligible one; FIFO order preserved within the group
    assert group == [a, c]
    assert adm.next_group(max_k=4, coalesce=True) == [b]
    assert adm.next_group(max_k=4, coalesce=True) == [d]
    for j in (a, b, c, d):
        adm.mark_done(j)
    assert adm.drained()


def test_request_caps_prices_with_ladder_rungs():
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.resilience.memory import estimate_bytes
    from abpoa_tpu.serve.admission import request_caps
    caps = request_caps(_params().finalize(), read_fastx(TEST_FA))
    assert caps["Qp"] == 128 and caps["N"] == 1024    # smallest rungs
    assert estimate_bytes(caps) > 0


def test_request_caps_agree_with_fused_planner():
    """Drift guard: admission pricing and the fused dispatch planner must
    key through the same rung formulas (compile.ladder is the shared
    definition site) — a formula change that reaches one but not the
    other would silently mis-price the serve byte gate."""
    from abpoa_tpu.align.fused_loop import plan_dispatch_footprint
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.serve.admission import request_caps
    abpt = _params(device="jax").finalize()
    for path in (TEST_FA, os.path.join(DATA_DIR, "seq.fa")):
        records = read_fastx(path)
        caps = request_caps(abpt, records)
        plan = plan_dispatch_footprint(abpt, [[r.seq for r in records]])
        for axis in ("N", "E", "A", "W", "Qp", "reads", "K", "gap_mode",
                     "m"):
            assert caps[axis] == plan[axis], (axis, caps, plan)


# --------------------------------------------------------------------- #
# endpoint contracts (in-process server, numpy backend)                  #
# --------------------------------------------------------------------- #

def test_align_bytes_identical_to_oracle_and_health_endpoints():
    srv = _start_server(workers=2)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, body, headers = _post(base, open(TEST_FA, "rb").read())
        assert code == 200
        assert body == _oracle_bytes()
        assert headers.get("X-Abpoa-Reads") == "4"
        code, h = _get_json(base, "/healthz")
        assert code == 200 and h["status"] == "ok"
        assert h["served"].get("ok") == 1 and h["degraded"] is None
        assert _get_json(base, "/readyz")[0] == 200
        assert _get_json(base, "/nope")[0] == 404
    finally:
        assert srv.stop()


def test_poisoned_request_is_400_worker_survives():
    from abpoa_tpu import obs
    srv = _start_server(workers=1)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, body, _ = _post(base, POISON_FQ)
        assert code == 400
        assert b"quality length" in body
        # empty body is a 400 too, never a crash
        assert _post(base, b"")[0] == 400
        assert _post(base, b"\x00\xff garbage \x9c")[0] == 400
        # the worker is alive and healthy work still completes
        code, body, _ = _post(base, open(TEST_FA, "rb").read())
        assert code == 200 and body == _oracle_bytes()
        # quarantine semantics: fault records, no crash
        assert obs.report().counters.get("faults.poisoned_set", 0) >= 1
    finally:
        srv.stop()


def test_queue_overflow_sheds_429_with_retry_after(monkeypatch):
    monkeypatch.setenv("ABPOA_TPU_SERVE_DELAY_S", "0.4")
    srv = _start_server(workers=1, queue_depth=1)
    base = f"http://127.0.0.1:{srv.port}"
    payload = open(TEST_FA, "rb").read()
    codes = []

    def post():
        codes.append(_post(base, payload))

    try:
        threads = [threading.Thread(target=post) for _ in range(5)]
        for t in threads:
            t.start()
            time.sleep(0.02)
        for t in threads:
            t.join()
        got = [c for c, _b, _h in codes]
        assert got.count(200) >= 1
        shed = [(c, h) for c, _b, h in codes if c == 429]
        assert shed, f"no 429s: {got}"
        assert all(int(h["Retry-After"]) >= 1 for _c, h in shed)
        # every 200 still byte-identical under pressure
        assert all(b == _oracle_bytes() for c, b, _h in codes if c == 200)
    finally:
        srv.stop()


def test_request_deadline_expires_as_504(monkeypatch):
    from abpoa_tpu import obs
    monkeypatch.setenv("ABPOA_TPU_SERVE_DELAY_S", "0.5")
    srv = _start_server(workers=1)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        t0 = time.perf_counter()
        code, body, _ = _post(base, open(TEST_FA, "rb").read(),
                              headers={"X-Abpoa-Deadline-S": "0.05"})
        dt = time.perf_counter() - t0
        assert code == 504
        assert dt < 5.0, "504 must come from the deadline, not the delay"
        assert obs.report().counters.get("faults.request_timeout", 0) >= 1
        # the worker was abandoned, not wedged: next request succeeds
        monkeypatch.setenv("ABPOA_TPU_SERVE_DELAY_S", "0")
        code, body, _ = _post(base, open(TEST_FA, "rb").read())
        assert code == 200 and body == _oracle_bytes()
    finally:
        srv.stop()


def test_metrics_endpoint_lints_with_serve_families():
    from abpoa_tpu.obs import metrics as M
    srv = _start_server(workers=1)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        _post(base, open(TEST_FA, "rb").read())
        _post(base, POISON_FQ)
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert M.lint_exposition(text) == []
        samples, types = M.parse_exposition(text)
        assert M.sample_value(samples, "abpoa_serve_requests_total",
                              status="ok") >= 1
        assert M.sample_value(samples, "abpoa_serve_requests_total",
                              status="poisoned") >= 1
        assert ("abpoa_serve_queue_depth", frozenset()) in samples
        assert ("abpoa_serve_inflight", frozenset()) in samples
        assert types.get("abpoa_serve_request_seconds") == "histogram"
        # the render-time quantile gauges cover the serve histogram too
        assert M.sample_value(samples, "abpoa_serve_request_seconds_quantile",
                              quantile="0.99") is not None
    finally:
        srv.stop()


def test_drain_in_process_rejects_new_finishes_inflight(monkeypatch):
    monkeypatch.setenv("ABPOA_TPU_SERVE_DELAY_S", "0.6")
    srv = _start_server(workers=1)
    base = f"http://127.0.0.1:{srv.port}"
    res = {}

    def post(key):
        res[key] = _post(base, open(TEST_FA, "rb").read())

    t = threading.Thread(target=post, args=("inflight",))
    t.start()
    time.sleep(0.2)        # request now executing (0.6 s service time)
    srv.begin_drain()
    code, h = _get_json(base, "/readyz")
    assert code == 503 and h["status"] == "draining"
    assert _get_json(base, "/healthz")[1]["status"] == "draining"
    post("after")
    t.join()
    assert res["after"][0] == 503
    assert res["inflight"][0] == 200
    assert res["inflight"][1] == _oracle_bytes()
    assert srv.drain(timeout=10)
    srv.shutdown_http()


# --------------------------------------------------------------------- #
# graceful drain, full-process contract (SIGTERM -> rc 0)                #
# --------------------------------------------------------------------- #

def test_sigterm_drains_flushes_and_exits_zero(tmp_path):
    """ISSUE 12 satellite: SIGTERM mid-request -> the in-flight request
    completes (byte-identical), subsequent requests get 503, the process
    exits 0 with metrics flushed and the final report archived."""
    metrics_path = str(tmp_path / "metrics.prom")
    archive_dir = str(tmp_path / "reports")
    env = dict(os.environ,
               ABPOA_TPU_SKIP_PROBE="1",
               ABPOA_TPU_ARCHIVE="1",
               ABPOA_TPU_ARCHIVE_DIR=archive_dir,
               ABPOA_TPU_SERVE_DELAY_S="1.2")
    proc = subprocess.Popen(
        [sys.executable, "-m", "abpoa_tpu.cli", "serve", "--port", "0",
         "--device", "numpy", "--workers", "1", "--metrics", metrics_path],
        cwd=REPO, env=env, stderr=subprocess.PIPE, text=True)
    try:
        port = None
        deadline = time.time() + 60
        while time.time() < deadline:
            line = proc.stderr.readline()
            if "listening on http://" in line:
                port = int(line.split("listening on http://")[1]
                           .split()[0].rsplit(":", 1)[1])
                break
        assert port, "server never printed its listening line"
        base = f"http://127.0.0.1:{port}"
        # readiness (numpy backend: no warm, near-instant)
        for _ in range(100):
            try:
                if urllib.request.urlopen(base + "/readyz",
                                          timeout=2).status == 200:
                    break
            except (urllib.error.URLError, OSError):
                time.sleep(0.1)
        res = {}

        def post(key):
            res[key] = _post(base, open(TEST_FA, "rb").read(), timeout=60)

        t = threading.Thread(target=post, args=("inflight",))
        t.start()
        time.sleep(0.4)            # in flight (1.2 s service time)
        proc.send_signal(signal.SIGTERM)
        time.sleep(0.2)
        post("after")              # during the drain window
        t.join(30)
        rc = proc.wait(timeout=60)
        stderr_rest = proc.stderr.read()
        assert rc == 0, f"drain exited rc={rc}:\n{stderr_rest[-2000:]}"
        assert res["inflight"][0] == 200
        assert res["inflight"][1] == _oracle_bytes()
        assert res["after"][0] == 503
        assert "drained clean" in stderr_rest
        assert "Traceback" not in stderr_rest
        # metrics flushed on the way out, lint-clean
        from abpoa_tpu.obs import metrics as M
        with open(metrics_path) as fp:
            final = fp.read()
        assert M.lint_exposition(final) == []
        samples, _t = M.parse_exposition(final)
        assert M.sample_value(samples, "abpoa_serve_requests_total",
                              status="ok") == 1
        # archive: one record per terminal request + the final process
        # report roll-up
        with open(os.path.join(archive_dir, "reports.jsonl")) as fp:
            recs = [json.loads(ln) for ln in fp.read().splitlines()]
        kinds = [r.get("kind") for r in recs]
        assert kinds.count("serve_request") == 1
        assert any(r.get("label") == "serve" for r in recs), \
            "final process report never archived"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


# --------------------------------------------------------------------- #
# loadgen + top panel                                                    #
# --------------------------------------------------------------------- #

def test_loadgen_open_loop_summary():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from loadgen import LoadGen
    srv = _start_server(workers=2, queue_depth=32)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        payload = open(TEST_FA, "rb").read()
        gen = LoadGen(base, [payload, POISON_FQ], rate=40.0, n=20,
                      timeout_s=30)
        s = gen.run()
        assert s["sent"] == 20 and s["errors"] == 0
        assert sum(s["status"].values()) == 20
        assert s["status"].get("400") == 10      # alternating payloads
        assert s["ok"] == 10
        assert all(b == _oracle_bytes() for b in gen.bodies_ok)
        assert s["latency_ms"]["p99"] is not None
        assert 0 < s["rate_achieved"] <= 120.0
    finally:
        srv.stop()


def test_top_renders_serve_panel():
    from abpoa_tpu.obs import metrics as M
    from abpoa_tpu.obs.top import render_frame
    expo = "\n".join([
        "# TYPE abpoa_serve_requests_total counter",
        'abpoa_serve_requests_total{status="ok"} 182',
        'abpoa_serve_requests_total{status="rejected"} 24',
        "# TYPE abpoa_serve_queue_depth gauge",
        "abpoa_serve_queue_depth 3",
        "# TYPE abpoa_serve_inflight gauge",
        "abpoa_serve_inflight 2",
        "# TYPE abpoa_serve_request_seconds_quantile gauge",
        'abpoa_serve_request_seconds_quantile{quantile="0.5"} 0.038',
        'abpoa_serve_request_seconds_quantile{quantile="0.95"} 0.081',
        'abpoa_serve_request_seconds_quantile{quantile="0.99"} 0.13',
        "# TYPE abpoa_runs_total counter",
        "abpoa_runs_total 1",
    ]) + "\n"
    samples, types = M.parse_exposition(expo)
    frame = render_frame(samples, types, "x.prom", 0.5)
    assert "serve" in frame
    assert "queue 3" in frame and "inflight 2" in frame
    assert "ok=182" in frame and "rejected=24" in frame
    assert "p99 130.00" in frame


def test_pool_backend_contains_worker_kill(monkeypatch):
    """--pool-workers execution backend (ISSUE 13): requests run in
    supervised processes; a SIGKILLed worker costs one requeue, never the
    service — and a hard deadline is a worker SIGKILL answering 504."""
    monkeypatch.setenv("ABPOA_TPU_SERVE_DELAY_S", "0.8")
    srv = _start_server(workers=2, pool_workers=1)
    base = f"http://{srv.host}:{srv.port}"
    body = open(TEST_FA, "rb").read()
    try:
        # healthy request through the pool: byte-identical
        code, got, _h = _post(base, body)
        assert code == 200 and got == _oracle_bytes()
        pool = _get_json(base, "/healthz")[1]["pool"]
        assert pool["workers"] == 1 and pool["jobs"] == 1

        # kill the worker MID-request: the job requeues on a fresh
        # worker and still answers 200 byte-identical
        res = {}

        def post_bg():
            res["code"], res["body"], _ = _post(base, body, timeout=60)

        t = threading.Thread(target=post_bg)
        t.start()
        time.sleep(0.4)  # inside the delay shim window
        pid = _get_json(base, "/healthz")[1]["pool"]["pids"][0]
        os.kill(pid, signal.SIGKILL)
        t.join()
        assert res["code"] == 200 and res["body"] == _oracle_bytes()
        pool = _get_json(base, "/healthz")[1]["pool"]
        assert pool["requeues"] == 1 and pool["workers"] == 1

        # a too-tight deadline is a hard worker SIGKILL -> 504
        code, _b, _h = _post(base, body,
                             headers={"X-Abpoa-Deadline-S": "0.3"},
                             timeout=30)
        assert code == 504
        pool = _get_json(base, "/healthz")[1]["pool"]
        assert pool["kills"] == 1
    finally:
        assert srv.stop()


# --------------------------------------------------------------------- #
# continuous batching (PR 17): late join at a round boundary             #
# --------------------------------------------------------------------- #

def test_serve_lockstep_late_join_byte_identity(monkeypatch):
    """With ONE worker and a slowed round clock, request A opens a churn
    group; B arrives mid-flight and can only be answered by boarding A's
    in-flight group at a round boundary. Both answers are byte-identical
    to the solo oracle, the join counter moves, the open-group registry
    shows on /healthz while live, and B's record names its join round."""
    monkeypatch.setenv("ABPOA_TPU_LOCKSTEP_MIN_QLEN", "0")
    monkeypatch.setenv("ABPOA_TPU_LOCKSTEP_ROUND_DELAY_S", "0.2")
    from abpoa_tpu.serve import AlignServer
    abpt = _params(device="jax")
    abpt.lockstep = "on"
    srv = AlignServer(abpt, port=0, workers=1)
    srv.start(warm="off")
    results = {}
    try:
        assert srv._churn, "split-lockstep churn route was not planned"
        base = f"http://{srv.host}:{srv.port}"
        with open(TEST_FA, "rb") as fp:
            body = fp.read()

        def post(tag):
            results[tag] = _post(base, body, timeout=120)

        ta = threading.Thread(target=post, args=("a",))
        ta.start()
        time.sleep(0.3)   # A mid-flight: 4 reads x 0.2 s rounds
        open_rungs = [g["rung"] for g in
                      _get_json(base, "/healthz")[1].get("open_groups", [])]
        tb = threading.Thread(target=post, args=("b",))
        tb.start()
        ta.join(120)
        tb.join(120)
        assert open_rungs, "no open group advertised while A was live"
        import urllib.request
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            expo = r.read().decode()
    finally:
        srv.stop()
    want = _oracle_bytes()
    for tag in ("a", "b"):
        st, got, _h = results[tag]
        assert st == 200, (tag, got)
        assert got == want, f"request {tag} diverged from the solo oracle"
    from abpoa_tpu.obs import metrics as M
    samples, _types = M.parse_exposition(expo)
    assert (M.sample_value(samples, "abpoa_lockstep_joins_total")
            or 0) >= 1, "B never boarded A's group"
    occ = M.sample_value(samples, "abpoa_lockstep_lane_occupancy")
    assert occ is not None and 0.0 < occ <= 1.0
