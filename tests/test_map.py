"""Map-mode tests (ISSUE 18): the fixed-graph read-to-graph mapping
workload — static DP tables built once, reads streamed through the
vmapped pow2 batch, GAF records byte-identical to the per-read host
oracle.

The parity grid runs the jitted kernel on CPU jax (signatures cached
across runs via .jax_cache); the serve endpoint tests run the numpy
host route (no jax import, fast startup) — the endpoint contract is
identical on both routes by construction, and tools/map_gate.py holds
the batched route to oracle byte-identity in CI."""
import io
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from make_sim import simulate

REF_LEN = 120        # tiny rung: the 18-case grid must compile cheaply
GRAPH_READS = 6
MAP_READS = 8


def _params(device="numpy", gap_mode=None, amb=False):
    from abpoa_tpu import constants as C
    from abpoa_tpu.params import Params
    abpt = Params()
    abpt.device = device
    if gap_mode == C.LINEAR_GAP:
        abpt.gap_open1, abpt.gap_open2 = 0, 0
    elif gap_mode == C.AFFINE_GAP:
        abpt.gap_open1, abpt.gap_ext1 = 4, 2
        abpt.gap_open2, abpt.gap_ext2 = 0, 0
    elif gap_mode == C.CONVEX_GAP:
        abpt.gap_open1, abpt.gap_ext1 = 4, 2
        abpt.gap_open2, abpt.gap_ext2 = 24, 1
    abpt.amb_strand = 1 if amb else 0
    return abpt.finalize()


def _encode(abpt, seq: str) -> np.ndarray:
    return abpt.char_to_code[
        np.frombuffer(seq.encode(), dtype=np.uint8)].astype(np.uint8)


_RC = str.maketrans("ACGT", "TGCA")


def _revcomp(seq: str) -> str:
    return seq.translate(_RC)[::-1]


@pytest.fixture(scope="module")
def sim_graph(tmp_path_factory):
    """ONE simulated read set split into a restored GFA graph (first
    reads) and a same-reference map stream with divergent read lengths
    (alternate reads truncated)."""
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa
    tmp = tmp_path_factory.mktemp("map")
    sim = str(tmp / "sim.fa")
    simulate(REF_LEN, GRAPH_READS + MAP_READS, 0.1, 1805, sim)
    recs = read_fastx(sim)
    abpt = Params()
    abpt.device = "numpy"
    # BEFORE finalize: use_read_ids (the P-line paths) derives from it
    abpt.out_cons, abpt.out_gfa = False, True
    abpt = abpt.finalize()
    buf = io.StringIO()
    msa(Abpoa(), abpt, recs[:GRAPH_READS], buf)
    gfa = str(tmp / "graph.gfa")
    with open(gfa, "w") as fp:
        fp.write(buf.getvalue())
    reads = []
    for i, r in enumerate(recs[GRAPH_READS:]):
        seq = r.seq if i % 2 == 0 else r.seq[:int(len(r.seq) * 0.6)]
        reads.append((r.name, seq))
    return gfa, reads


def _host_gaf(gfa, reads, abpt):
    from abpoa_tpu.io.gaf import gaf_record
    from abpoa_tpu.parallel.map_driver import (load_static_graph,
                                               map_read_host)
    host = _params("numpy", amb=bool(abpt.amb_strand))
    host.gap_open1, host.gap_ext1 = abpt.gap_open1, abpt.gap_ext1
    host.gap_open2, host.gap_ext2 = abpt.gap_open2, abpt.gap_ext2
    host = host.finalize()
    ab, static = load_static_graph(gfa, host)
    lines = []
    for name, seq in reads:
        q = _encode(host, seq)
        res, strand = map_read_host(ab.graph, host, q)
        lines.append(gaf_record(name, q, res, static.base_by_nid,
                                strand=strand))
    return "\n".join(lines) + "\n"


def _batched_gaf(gfa, reads, abpt, k_cap):
    from abpoa_tpu.io.gaf import gaf_record
    from abpoa_tpu.parallel.map_driver import (load_static_graph,
                                               map_reads_split)
    ab, static = load_static_graph(gfa, abpt)
    queries = [_encode(abpt, seq) for _name, seq in reads]
    out = map_reads_split(static, queries, abpt, k_cap=k_cap)
    lines = []
    for (name, _seq), q, res in zip(reads, queries, out):
        assert res is not None
        lines.append(gaf_record(name, q, res[0], static.base_by_nid,
                                strand=res[1]))
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------- #
# parity grid: gap regime x K x amb-strand, divergent read lengths            #
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("k_cap", [1, 4, 8])
@pytest.mark.parametrize("gap_mode", ["linear", "affine", "convex"])
@pytest.mark.parametrize("amb", [False, True])
def test_map_parity_grid(sim_graph, gap_mode, k_cap, amb):
    from abpoa_tpu import constants as C
    mode = {"linear": C.LINEAR_GAP, "affine": C.AFFINE_GAP,
            "convex": C.CONVEX_GAP}[gap_mode]
    gfa, reads = sim_graph
    if amb:
        # flip half the reads to the minus strand: the amb-strand second
        # dispatch must recover them, byte-identically to the host rule
        reads = [(n, s if i % 2 == 0 else _revcomp(s))
                 for i, (n, s) in enumerate(reads)]
    abpt = _params("jax", gap_mode=mode, amb=amb)
    got = _batched_gaf(gfa, reads, abpt, k_cap)
    want = _host_gaf(gfa, reads, abpt)
    assert got == want
    if amb:
        assert "\t-\t" in got   # some read actually mapped minus-strand


def test_map_off_rung_read_skipped(sim_graph):
    """A read past the pinned Qp rung retires as None; the rest of the
    stream still maps, byte-identical to the oracle."""
    from abpoa_tpu.parallel.map_driver import (load_static_graph,
                                               map_reads_split)
    gfa, reads = sim_graph
    abpt = _params("jax")
    ab, static = load_static_graph(gfa, abpt)
    queries = [_encode(abpt, seq) for _n, seq in reads]
    long_q = np.zeros(4000, dtype=np.uint8)
    out = map_reads_split(static, [long_q] + queries, abpt, k_cap=4,
                          Qp=256)
    assert out[0] is None
    assert all(r is not None for r in out[1:])


# --------------------------------------------------------------------------- #
# restore -> map -> restore round-trip: the graph is immutable                #
# --------------------------------------------------------------------------- #

def test_restore_map_restore_roundtrip(sim_graph):
    from abpoa_tpu.io.output import generate_gfa
    from abpoa_tpu.parallel.map_driver import load_static_graph

    def export(ab, abpt):
        from abpoa_tpu.params import Params
        out = Params()
        out.device = abpt.device
        out.out_cons, out.out_gfa = False, True
        out = out.finalize()
        buf = io.StringIO()
        generate_gfa(ab.graph, out, ab.names, ab.is_rc, lambda: None, buf)
        return buf.getvalue()

    gfa, reads = sim_graph
    abpt = _params("jax")
    ab, _static = load_static_graph(gfa, abpt)
    before = export(ab, abpt)
    first = _batched_gaf(gfa, reads, abpt, k_cap=4)
    assert export(ab, abpt) == before       # mapping mutated nothing
    # a second restore of the same file maps the same bytes
    assert _batched_gaf(gfa, reads, abpt, k_cap=4) == first


def test_static_tables_share_graph_half(sim_graph):
    """stamp_query reuses the graph-half arrays by reference: per-read
    stamping must never rebuild the adjacency scatter."""
    from abpoa_tpu.parallel.map_driver import load_static_graph
    gfa, reads = sim_graph
    abpt = _params("jax")
    _ab, static = load_static_graph(gfa, abpt)
    q1, q2 = _encode(abpt, reads[0][1]), _encode(abpt, reads[1][1])
    t1 = static.tables_for(q1, 256)
    t2 = static.tables_for(q2, 256)
    assert t1["pre_idx"] is t2["pre_idx"]


# --------------------------------------------------------------------------- #
# scheduler + admission                                                       #
# --------------------------------------------------------------------------- #

def test_plan_route_map_ignores_qlen_gate():
    """The map route has no 1500 bp serial-vs-lockstep crossover: a map
    deployment pinned its graph, so short reads still batch."""
    from abpoa_tpu.parallel.scheduler import plan_route
    route = plan_route(_params("jax"), 8, workload="map", qlen=100)
    assert route.kind == "map"
    assert route.k_cap >= 1
    assert plan_route(_params("numpy"), 8, workload="map").kind == "serial"


def test_map_request_bytes_prices_reads_only():
    """Admission pricing for /map is linear in the read plane — the graph
    plane was paid once at restore, not per request."""
    from abpoa_tpu.serve.admission import map_request_bytes

    class R:
        def __init__(self, seq):
            self.seq = seq

    abpt = _params("numpy")
    one = map_request_bytes(abpt, [R("A" * 200)], n_rows=500)
    two = map_request_bytes(abpt, [R("A" * 200)] * 2, n_rows=500)
    assert one > 0
    assert two == 2 * one


def test_ladder_declares_map_rungs():
    from abpoa_tpu.compile.ladder import LADDER, QUICK_TIER
    assert "run_dp_chunk[map]" in LADDER
    assert any(a.entry == "run_dp_chunk" and a.k == 8 for a in QUICK_TIER)


# --------------------------------------------------------------------------- #
# POST /map endpoint contract (numpy host route)                              #
# --------------------------------------------------------------------------- #

HANDCRAFT_GFA = ("H\tVN:Z:1.0\n"
                 "S\ts1\tACGTACGTACGTACGTACGT\n"
                 "S\ts2\tTTGGCCAATTGGCCAATTGG\n"
                 "P\tread1\ts1+,s2+\t*\n"
                 "P\tread2\ts1+\t*\n")


def _start_map_server(tmp_path, gfa_text=HANDCRAFT_GFA, **kw):
    from abpoa_tpu.serve import AlignServer
    path = str(tmp_path / "hand.gfa")
    with open(path, "w") as fp:
        fp.write(gfa_text)
    srv = AlignServer(_params("numpy"), port=0, map_graph=path, **kw)
    srv.start(warm="off")
    return srv


def _post(srv, path, body, headers=None):
    req = urllib.request.Request(
        f"http://{srv.host}:{srv.port}{path}", data=body, method="POST",
        headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def test_serve_map_returns_gaf(tmp_path):
    srv = _start_map_server(tmp_path)
    try:
        body = b">q1\nACGTACGTACGTACGTACGTTTGGCCAATTGGCCAATTGG\n"
        code, out, hdrs = _post(srv, "/map", body)
        assert code == 200
        assert hdrs.get("Content-Type", "").startswith("text/x-gaf")
        assert hdrs.get("X-Abpoa-Reads") == "1"
        fields = out.decode().strip().split("\t")
        assert fields[0] == "q1"
        assert fields[4] == "+"
        assert any(f.startswith("cg:Z:") for f in fields)
        # /align still serves consensus on the same server
        code2, out2, hdrs2 = _post(srv, "/align", body)
        assert code2 == 200
        assert hdrs2.get("Content-Type", "").startswith("text/x-fasta")
    finally:
        srv.stop()


def test_serve_map_matches_host_oracle(tmp_path, sim_graph):
    gfa, reads = sim_graph
    with open(gfa) as fp:
        gfa_text = fp.read()
    srv = _start_map_server(tmp_path, gfa_text=gfa_text)
    try:
        body = "".join(f">{n}\n{s}\n" for n, s in reads).encode()
        code, out, _hdrs = _post(srv, "/map", body)
        assert code == 200
        assert out.decode() == _host_gaf(gfa, reads, _params("numpy"))
    finally:
        srv.stop()


def test_serve_map_without_graph_400():
    from abpoa_tpu.serve import AlignServer
    srv = AlignServer(_params("numpy"), port=0)
    srv.start(warm="off")
    try:
        code, out, _ = _post(srv, "/map", b">q\nACGT\n")
        assert code == 400
        assert b"map graph" in out
    finally:
        srv.stop()


def test_serve_map_oversized_read_400(tmp_path, monkeypatch):
    monkeypatch.setenv("ABPOA_TPU_MAP_MAX_QLEN", "32")
    srv = _start_map_server(tmp_path)
    try:
        code, out, _ = _post(srv, "/map", b">big\n" + b"A" * 64 + b"\n")
        assert code == 400
        assert b"map read cap" in out
        # a read under the cap still maps fine on the same connection
        code2, _out2, _ = _post(srv, "/map", b">ok\nACGTACGTACGT\n")
        assert code2 == 200
    finally:
        srv.stop()


def test_serve_healthz_advertises_map_graph(tmp_path):
    import json
    srv = _start_map_server(tmp_path)
    try:
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/healthz", timeout=10) as r:
            health = json.loads(r.read())
        mg = health.get("map_graph") or {}
        assert mg.get("nodes", 0) > 2
        assert mg.get("batched") is False   # numpy host route
    finally:
        srv.stop()
