"""C++ DP kernel (device=native) byte-golden tests."""
import io
import os

import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def _native_available():
    try:
        from abpoa_tpu.native import load
        return load() is not None
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _native_available(),
                                reason="native core unavailable")


def run_cli(args):
    out = io.StringIO()
    from abpoa_tpu.cli import build_parser, args_to_params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    ns = build_parser().parse_args(args)
    abpt = args_to_params(ns).finalize()
    ab = Abpoa()
    msa_from_file(ab, abpt, ns.input, out)
    assert getattr(ab.graph, "is_native", False), "native path not engaged"
    return out.getvalue()


def golden(name):
    with open(os.path.join(GOLDEN_DIR, name)) as fp:
        return fp.read()


def test_native_consensus():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "--device", "native"])
    assert got == golden("ref_consensus.txt")


def test_native_heter_2cons():
    got = run_cli([os.path.join(DATA_DIR, "heter.fa"), "-d2", "--device", "native"])
    assert got == golden("ref_heter.txt")


def test_native_seeded_progressive():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "-S", "-p", "--device", "native"])
    assert got == golden("seq_Sp.txt")


def test_native_rc_mixed_seeded():
    got = run_cli([os.path.join(DATA_DIR, "rcmix.fa"), "-s", "-S", "-n", "200",
                   "--device", "native"])
    assert got == golden("rcmix_sS.txt")


def test_native_local_mode():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "-m1", "--device", "native"])
    assert got == golden("seq_m1.txt")


def test_native_extend_mode():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "-m2", "--device", "native"])
    assert got == golden("seq_m2.txt")
