"""C++ DP kernel (device=native) byte-golden tests."""
import io
import os

import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def _native_available():
    try:
        from abpoa_tpu.native import load
        return load() is not None
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _native_available(),
                                reason="native core unavailable")


def run_cli(args):
    out = io.StringIO()
    from abpoa_tpu.cli import build_parser, args_to_params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    ns = build_parser().parse_args(args)
    abpt = args_to_params(ns).finalize()
    ab = Abpoa()
    msa_from_file(ab, abpt, ns.input, out)
    assert getattr(ab.graph, "is_native", False), "native path not engaged"
    return out.getvalue()


def golden(name):
    with open(os.path.join(GOLDEN_DIR, name)) as fp:
        return fp.read()


def test_native_consensus():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "--device", "native"])
    assert got == golden("ref_consensus.txt")


def test_native_heter_2cons():
    got = run_cli([os.path.join(DATA_DIR, "heter.fa"), "-d2", "--device", "native"])
    assert got == golden("ref_heter.txt")


def test_native_seeded_progressive():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "-S", "-p", "--device", "native"])
    assert got == golden("seq_Sp.txt")


def test_native_rc_mixed_seeded():
    got = run_cli([os.path.join(DATA_DIR, "rcmix.fa"), "-s", "-S", "-n", "200",
                   "--device", "native"])
    assert got == golden("rcmix_sS.txt")


def test_native_local_mode():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "-m1", "--device", "native"])
    assert got == golden("seq_m1.txt")


def test_native_extend_mode():
    got = run_cli([os.path.join(DATA_DIR, "seq.fa"), "-m2", "--device", "native"])
    assert got == golden("seq_m2.txt")


@pytest.mark.parametrize("extra", [
    {},                                              # convex
    {"gap_open2": 0},                                # affine
    {"gap_open1": 0, "gap_open2": 0},                # linear
    {"align_mode": 1},                               # local (-G lead seeding)
    {"align_mode": 2, "zdrop": 20},                  # extend + Z-drop
], ids=["convex", "affine", "linear", "local", "extend-zdrop"])
def test_native_inc_path_score_matches_oracle(extra, tmp_path):
    """-G path scores run natively (no oracle fallback; VERDICT r3 item 6):
    byte parity with the numpy oracle across gap regimes and align modes
    (reference inc_path_score semantics, abpoa_graph.c:429-437)."""
    import numpy as np
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_property import _random_reads
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    rng = np.random.default_rng(11)
    reads = _random_reads(rng, 6, 150)
    fa = tmp_path / "g.fa"
    fa.write_text("".join(
        f">r{i}\n" + "".join("ACGT"[b] for b in r) + "\n"
        for i, r in enumerate(reads)))

    def run(device):
        abpt = Params()
        abpt.device = device
        abpt.inc_path_score = True
        abpt.out_msa = True
        for k, v in extra.items():
            setattr(abpt, k, v)
        abpt.finalize()
        ab = Abpoa()
        out = io.StringIO()
        msa_from_file(ab, abpt, str(fa), out)
        return out.getvalue(), getattr(ab.graph, "is_native", False)

    out_np, nat_np = run("numpy")
    assert not nat_np

    import abpoa_tpu.align.oracle as oracle_mod
    calls = {"n": 0}
    orig = oracle_mod.align_sequence_to_subgraph_numpy
    oracle_mod.align_sequence_to_subgraph_numpy = (
        lambda *a, **k: (calls.__setitem__("n", calls["n"] + 1), orig(*a, **k))[1])
    try:
        out_nat, nat = run("native")
    finally:
        oracle_mod.align_sequence_to_subgraph_numpy = orig
    assert nat, "native graph not engaged for -G"
    assert out_np == out_nat
    assert calls["n"] == 0, "native path silently fell back to the oracle"


def test_native_int16_plane_parity(tmp_path):
    """int16 plane STORAGE (selected by the reference's score-width bound,
    abpoa_align_simd.c:1284-1302) must be byte-identical to forced-int32
    planes across modes, gap regimes, and outputs — the saturating store
    keeps decayed -inf cells below every real score."""
    import io
    import subprocess
    import sys
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    sim = str(tmp_path / "i16.fa")
    subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "make_sim.py"),
         "--ref-len", "800", "--n-reads", "12", "--err", "0.12",
         "--seed", "77", "--out", sim], check=True)

    def run(path, flags, force32):
        env_key = "ABPOA_TPU_NATIVE_I32"
        if force32:
            os.environ[env_key] = "1"
        else:
            os.environ.pop(env_key, None)
        try:
            abpt = Params()
            abpt.device = "native"
            for k, v in flags.items():
                setattr(abpt, k, v)
            abpt.finalize()
            out = io.StringIO()
            msa_from_file(Abpoa(), abpt, path, out)
            return out.getvalue()
        finally:
            os.environ.pop(env_key, None)

    cases = [{}, {"gap_open2": 0}, {"gap_open1": 0, "gap_open2": 0},
             {"align_mode": 1}, {"align_mode": 2, "zdrop": 20},
             {"out_msa": True, "out_cons": False}]
    for path in (os.path.join(DATA_DIR, "seq.fa"), sim):
        for flags in cases:
            assert run(path, flags, False) == run(path, flags, True), \
                (path, flags)
