"""Dispatch-layer rewrite tests (round 14).

- structural jaxpr scatter budget: `_fuse_vectorized` lowers to <= 4
  scatter sites (the collapse can't silently regress)
- spill-scatter convention drift test (fused_loop.spill_scatter)
- split-lockstep parity: collapsed-scatter fused path AND the split
  lockstep driver are byte-identical to the numpy oracle across the
  linear/affine/convex kernel grid and K in {1, 2, 4} with
  divergent-length sets (born-finished padding included)
- scheduler unit behavior: route kinds + the noop-fraction K cap
- scheduler/noop Prometheus families + `top` panel rendering
"""
import io
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tests.conftest import DATA_DIR  # noqa: E402

from abpoa_tpu.params import Params  # noqa: E402


def _params(device="jax", **kw):
    abpt = Params()
    abpt.device = device
    for k, v in kw.items():
        setattr(abpt, k, v)
    abpt.finalize()
    return abpt


def _random_sets(rng, sizes, qlen_lo=40, qlen_hi=200, err=0.12):
    """Divergent-length read sets: set i has sizes[i] reads of a mutated
    common reference whose length differs per set."""
    sets, wsets = [], []
    for i, n in enumerate(sizes):
        L = int(rng.integers(qlen_lo, qlen_hi))
        ref = rng.integers(0, 4, L).astype(np.uint8)
        reads = []
        for _ in range(n):
            r = ref.copy()
            n_mut = max(1, int(err * L))
            posn = rng.integers(0, L, n_mut)
            r[posn] = rng.integers(0, 4, n_mut)
            reads.append(r)
        sets.append(reads)
        wsets.append([np.ones(len(r), dtype=np.int64) for r in reads])
    return sets, wsets


def _host_graph_consensus(abpt_kw, seqs, weights):
    from abpoa_tpu.cons.consensus import generate_consensus
    from abpoa_tpu.io.output import output_fx_consensus
    from abpoa_tpu.pipeline import Abpoa, poa
    abpt = _params(device="numpy", **abpt_kw)
    ab = Abpoa()
    for r in seqs:
        ab.append_read(seq="x" * len(r))
    poa(ab, abpt, seqs, weights, 0)
    cons = generate_consensus(ab.graph, abpt, len(seqs))
    out = io.StringIO()
    output_fx_consensus(cons, abpt, out)
    return out.getvalue()


def _split_consensus(abpt_kw, seq_sets, weight_sets):
    from abpoa_tpu.cons.consensus import generate_consensus
    from abpoa_tpu.io.output import output_fx_consensus
    from abpoa_tpu.parallel.lockstep import progressive_poa_split_batch
    abpt = _params(device="jax", **abpt_kw)
    outs = progressive_poa_split_batch(seq_sets, weight_sets, abpt)
    texts = []
    for i, o in enumerate(outs):
        assert o is not None, f"set {i} fell back"
        pg, _is_rc = o
        cons = generate_consensus(pg, abpt, len(seq_sets[i]))
        buf = io.StringIO()
        output_fx_consensus(cons, abpt, buf)
        texts.append(buf.getvalue())
    return texts


# --------------------------------------------------------------------- #
# structural scatter budget                                             #
# --------------------------------------------------------------------- #

def _count_scatters(jaxpr, counts):
    import jax
    for eq in jaxpr.eqns:
        if eq.primitive.name.startswith("scatter"):
            counts[eq.primitive.name] = counts.get(eq.primitive.name, 0) + 1
        for v in eq.params.values():
            if hasattr(v, "jaxpr"):
                _count_scatters(v.jaxpr, counts)
            if isinstance(v, (list, tuple)):
                for vv in v:
                    if hasattr(vv, "jaxpr"):
                        _count_scatters(vv.jaxpr, counts)
    return counts


def test_fuse_vectorized_scatter_budget():
    """The tentpole pin: _fuse_vectorized lowers to <= 4 scatter sites
    (path plane, out-adjacency, in-adjacency, aligned-group). A fifth
    scatter creeping back in is the regression this guards against."""
    import jax
    import jax.numpy as jnp
    from abpoa_tpu.align.fused_loop import _fuse_vectorized, init_fused_state
    T, Qp = 64, 64
    st = init_fused_state(256, 8, 8)
    jx = jax.make_jaxpr(_fuse_vectorized)(
        st.g, jnp.zeros(T, jnp.int32), jnp.zeros(T, jnp.int32),
        jnp.int32(10), jnp.zeros(Qp, jnp.int32), jnp.int32(10),
        jnp.ones(Qp, jnp.int32))
    counts = _count_scatters(jx.jaxpr, {})
    assert sum(counts.values()) <= 4, counts


def test_fuse_vectorized_scatter_budget_vmapped():
    """The budget must hold under vmap too (the on-chip lockstep shape):
    batching must not multiply scatter sites."""
    import jax
    import jax.numpy as jnp
    from abpoa_tpu.align.fused_loop import _fuse_vectorized, init_fused_state

    K, T, Qp = 4, 64, 64
    st = init_fused_state(256, 8, 8)
    gK = jax.tree.map(lambda x: jnp.stack([x] * K), st.g)
    jx = jax.make_jaxpr(jax.vmap(_fuse_vectorized))(
        gK, jnp.zeros((K, T), jnp.int32), jnp.zeros((K, T), jnp.int32),
        jnp.zeros(K, jnp.int32), jnp.zeros((K, Qp), jnp.int32),
        jnp.zeros(K, jnp.int32), jnp.ones((K, Qp), jnp.int32))
    counts = _count_scatters(jx.jaxpr, {})
    assert sum(counts.values()) <= 4, counts


def test_spill_scatter_convention():
    """The hoisted extra-slot convention: invalid rows drop, valid rows
    land, for every op flavor, 1-D and N-D operands — the drift test for
    the sites that share fused_loop.spill_scatter."""
    import jax.numpy as jnp
    from abpoa_tpu.align.fused_loop import spill_scatter
    arr = jnp.zeros(4, jnp.int32)
    idx = jnp.asarray([0, 1, 2, 3])
    valid = jnp.asarray([True, False, True, False])
    vals = jnp.asarray([5, 6, 7, 8], jnp.int32)
    out = np.asarray(spill_scatter(arr, idx, valid, vals))
    assert out.tolist() == [5, 0, 7, 0]
    # add-op accumulates only valid rows, even with duplicate indices
    out = np.asarray(spill_scatter(arr, jnp.asarray([2, 2, 2, 2]),
                                   valid, vals, op="add"))
    assert out.tolist() == [0, 0, 12, 0]
    # out-of-range VALID index also drops (the N+1 semantics): an index
    # equal to len(arr) routes to the appended spill slot
    out = np.asarray(spill_scatter(arr, jnp.asarray([4, 0, 4, 1]),
                                   jnp.ones(4, bool), vals))
    assert out.tolist() == [6, 8, 0, 0]
    # 2-D rows
    arr2 = jnp.zeros((3, 2), jnp.int32)
    vals2 = jnp.ones((2, 2), jnp.int32)
    out = np.asarray(spill_scatter(arr2, jnp.asarray([1, 2]),
                                   jnp.asarray([True, False]), vals2))
    assert out.tolist() == [[0, 0], [1, 1], [0, 0]]


# --------------------------------------------------------------------- #
# split-lockstep parity                                                 #
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("kw", [
    {},                                    # convex
    {"gap_open2": 0},                      # affine
    {"gap_open1": 0, "gap_open2": 0},      # linear
], ids=["convex", "affine", "linear"])
@pytest.mark.parametrize("k", [1, 2, 4])
def test_split_lockstep_parity_grid(kw, k):
    """Split-lockstep output byte-identical to the numpy oracle across
    the kernel grid and K in {1,2,4}, with divergent-length sets (set
    sizes differ, so sets finish at different rounds and the survivors
    ride born-finished padding lanes)."""
    rng = np.random.default_rng(123 + k)
    sizes = [3, 6, 2, 5][:k]
    seq_sets, weight_sets = _random_sets(rng, sizes)
    got = _split_consensus(kw, seq_sets, weight_sets)
    for i in range(k):
        want = _host_graph_consensus(kw, seq_sets[i], weight_sets[i])
        assert got[i] == want, f"set {i} diverged (K={k}, {kw})"


def test_split_lockstep_data_files():
    """Shipped data files as one divergent 3-set group vs the host loop."""
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.pipeline import Abpoa, _ingest_records
    abpt = _params()
    seq_sets, weight_sets = [], []
    for fn in ("seq.fa", "test.fa", "heter.fa"):
        seqs, weights = _ingest_records(
            Abpoa(), abpt, read_fastx(os.path.join(DATA_DIR, fn)))
        seq_sets.append(seqs)
        weight_sets.append(weights)
    got = _split_consensus({}, seq_sets, weight_sets)
    for i in range(3):
        want = _host_graph_consensus({}, seq_sets[i], weight_sets[i])
        assert got[i] == want


def test_split_lockstep_amb_strand():
    """Ambiguous-strand rescue inside the split driver: rc reads are
    realigned in the extra batched dispatch and annotated, byte-matching
    the host loop (which must actually flip at least one read)."""
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.parallel.lockstep import progressive_poa_split_batch
    from abpoa_tpu.pipeline import Abpoa, _ingest_records, poa
    path = os.path.join(DATA_DIR, "rcmix.fa")
    abpt = _params(amb_strand=1)
    seqs, weights = _ingest_records(Abpoa(), abpt, read_fastx(path))
    outs = progressive_poa_split_batch([seqs, seqs], [weights, weights],
                                       abpt)
    abpt_h = _params(device="numpy", amb_strand=1)
    ab = Abpoa()
    for r in seqs:
        ab.append_read(seq="x" * len(r))
    poa(ab, abpt_h, seqs, weights, 0)
    assert any(ab.is_rc), "fixture no longer exercises the rc path"
    for o in outs:
        assert o is not None
        _pg, is_rc = o
        assert is_rc == ab.is_rc


def test_split_lockstep_via_run_batch(tmp_path):
    """`-l` end to end: device=jax + --lockstep on routes through the
    scheduler to the split driver on this CPU host, and the emitted bytes
    match the serial numpy runner exactly."""
    from abpoa_tpu.parallel import run_batch
    from abpoa_tpu.parallel import scheduler
    files = [os.path.join(DATA_DIR, f)
             for f in ("seq.fa", "test.fa", "heter.fa")]

    abpt = _params(device="numpy")
    want = io.StringIO()
    run_batch(files, abpt, want, devices=[None])

    abpt = _params(device="jax", lockstep="on")
    scheduler.reset()
    route = scheduler.plan_route(abpt, len(files))
    assert route.kind == "lockstep" and route.impl == "split"
    got = io.StringIO()
    run_batch(files, abpt, got)
    assert got.getvalue() == want.getvalue()


# --------------------------------------------------------------------- #
# continuous batching: round-boundary lane churn (round 17)             #
# --------------------------------------------------------------------- #

class _ScriptedChurn:
    """Test hook: boards scripted joiners / evicts lanes at fixed round
    boundaries and records every retire delivery as (result, round)."""

    def __init__(self, joins=None, evict_at=None):
        self.joins = dict(joins or {})
        self.evict_at = dict(evict_at or {})
        self.retired = {}
        self.rounds = []

    def on_round(self, round_i, live_sids):
        self.rounds.append((round_i, list(live_sids)))
        return (self.evict_at.pop(round_i, set()),
                self.joins.pop(round_i, []))

    def on_retire(self, sid, result, round_i):
        assert sid not in self.retired, f"double retire for lane {sid}"
        self.retired[sid] = (result, round_i)


def _consensus_text(abpt, pg, n_reads):
    from abpoa_tpu.cons.consensus import generate_consensus
    from abpoa_tpu.io.output import output_fx_consensus
    cons = generate_consensus(pg, abpt, n_reads)
    buf = io.StringIO()
    output_fx_consensus(cons, abpt, buf)
    return buf.getvalue()


@pytest.mark.parametrize("join_round", [1, 4, 8],
                         ids=["first", "mid", "last"])
def test_split_lockstep_churn_join_parity(join_round):
    """Lane-churn parity grid: a joiner boarding at the first / a mid /
    the last round of a divergent-length group is byte-identical to its
    solo numpy oracle, the initial sets stay byte-identical, and the
    short set retires EARLY (the round its last read fuses), not at
    group end."""
    from abpoa_tpu.parallel.lockstep import progressive_poa_split_batch
    rng = np.random.default_rng(2026)
    seq_sets, weight_sets = _random_sets(rng, [3, 8])
    # qlen_hi=120 keeps the joiner on the group's Qp rung (>= 128) always
    j_sets, j_wsets = _random_sets(rng, [4], qlen_hi=120)
    hook = _ScriptedChurn(
        joins={join_round: [(100, j_sets[0], j_wsets[0])]})
    abpt = _params(device="jax")
    outs = progressive_poa_split_batch(seq_sets, weight_sets, abpt,
                                       churn=hook)
    for i in (0, 1):
        assert outs[i] is not None
        pg, _rc = outs[i]
        got = _consensus_text(abpt, pg, len(seq_sets[i]))
        assert got == _host_graph_consensus({}, seq_sets[i],
                                            weight_sets[i]), i
    # 3-read lane retires at round 3, 8-read lane at round 8
    assert hook.retired[0][1] == 3
    assert hook.retired[1][1] == 8
    # the joiner's result arrives only via the hook: seeded the round it
    # boards, one DP round per remaining read
    res, r = hook.retired[100]
    assert res is not None and r == join_round + 3
    pg, _rc = res
    got = _consensus_text(abpt, pg, len(j_sets[0]))
    assert got == _host_graph_consensus({}, j_sets[0], j_wsets[0])


def test_split_lockstep_churn_amb_strand_joiner():
    """An ambiguous-strand set boarding mid-flight rides the batched
    rc-rescue dispatch like any initial lane: rc annotations and emitted
    bytes match the host loop exactly."""
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.parallel.lockstep import progressive_poa_split_batch
    from abpoa_tpu.pipeline import Abpoa, _ingest_records, poa
    path = os.path.join(DATA_DIR, "rcmix.fa")
    abpt = _params(amb_strand=1)
    seqs, weights = _ingest_records(Abpoa(), abpt, read_fastx(path))
    hook = _ScriptedChurn(joins={2: [(7, seqs, weights)]})
    outs = progressive_poa_split_batch([seqs], [weights], abpt, churn=hook)
    abpt_h = _params(device="numpy", amb_strand=1)
    ab = Abpoa()
    for r in seqs:
        ab.append_read(seq="x" * len(r))
    poa(ab, abpt_h, seqs, weights, 0)
    assert any(ab.is_rc), "fixture no longer exercises the rc path"
    assert outs[0] is not None and outs[0][1] == ab.is_rc
    res, _r = hook.retired[7]
    assert res is not None and res[1] == ab.is_rc
    want = _host_graph_consensus({"amb_strand": 1}, seqs, weights)
    assert _consensus_text(abpt, outs[0][0], len(seqs)) == want
    assert _consensus_text(abpt, res[0], len(seqs)) == want


def test_split_lockstep_churn_evict_and_off_rung():
    """Boundary eviction drops a lane without a result (the hook owns
    answering it); an off-rung joiner is rejected via on_retire(None)
    instead of forcing a new Qp compile rung; a duplicate sid raises."""
    from abpoa_tpu.compile.ladder import qp_rung
    from abpoa_tpu.parallel.lockstep import progressive_poa_split_batch
    rng = np.random.default_rng(5)
    seq_sets, weight_sets = _random_sets(rng, [3, 5])
    Qp = qp_rung(max(len(s) for ss in seq_sets for s in ss))
    long_read = rng.integers(0, 4, Qp + 10).astype(np.uint8)
    hook = _ScriptedChurn(
        joins={2: [(50, [long_read],
                    [np.ones(len(long_read), np.int64)])]},
        evict_at={2: {0}})
    abpt = _params(device="jax")
    outs = progressive_poa_split_batch(seq_sets, weight_sets, abpt,
                                       churn=hook)
    assert outs[0] is None and 0 not in hook.retired
    assert hook.retired[50] == (None, 2)
    pg, _rc = outs[1]
    assert _consensus_text(abpt, pg, len(seq_sets[1])) == \
        _host_graph_consensus({}, seq_sets[1], weight_sets[1])
    hook2 = _ScriptedChurn(joins={1: [(0, seq_sets[0], weight_sets[0])]})
    with pytest.raises(ValueError):
        progressive_poa_split_batch(seq_sets, weight_sets, abpt,
                                    churn=hook2)


# --------------------------------------------------------------------- #
# scheduler                                                             #
# --------------------------------------------------------------------- #

def test_scheduler_noop_k_cap():
    from abpoa_tpu.parallel.scheduler import noop_k_cap
    assert noop_k_cap(8, 0.0) == 8
    assert noop_k_cap(8, 0.24) == 8
    assert noop_k_cap(8, 0.25) == 4
    assert noop_k_cap(8, 0.5) == 2
    assert noop_k_cap(8, 0.75) == 1
    assert noop_k_cap(8, 1.0) == 1
    assert noop_k_cap(1, 0.9) == 1


def test_scheduler_routes(monkeypatch):
    from abpoa_tpu.parallel import scheduler
    scheduler.reset()
    # host device -> lockstep ineligible -> serial on this 1-core host
    abpt = _params(device="numpy")
    r = scheduler.plan_route(abpt, 4)
    assert r.kind in ("serial", "pool")
    # explicit workers make multi-set host batches a pool
    abpt.workers = 3
    r = scheduler.plan_route(abpt, 4)
    assert r.kind == "pool" and r.workers == 3
    # lockstep opt-in on a CPU host -> split lockstep
    abpt = _params(device="jax", lockstep="on")
    r = scheduler.plan_route(abpt, 4)
    assert r.kind == "lockstep" and r.impl == "split"
    # measured divergence caps K
    scheduler.reset()
    scheduler.observe_noop_fraction(0.6)
    r2 = scheduler.plan_route(abpt, 4)
    assert r2.k_cap < r.k_cap
    # explicit workers + many sets -> hybrid (pool of lockstep groups)
    scheduler.reset()
    abpt.workers = 2
    r = scheduler.plan_route(abpt, 32)
    assert r.kind == "hybrid" and r.workers == 2 and r.k_cap >= 1
    abpt.workers = 0
    # forced impl override
    monkeypatch.setenv("ABPOA_TPU_LOCKSTEP_IMPL", "device")
    r = scheduler.plan_route(abpt, 4)
    assert r.impl == "device"
    scheduler.reset()


def test_scheduler_metrics_and_top_panel():
    """Route decisions + noop EWMA surface as Prometheus families, lint
    clean, and render in the `top` scheduler panel."""
    from abpoa_tpu.obs import metrics as M
    from abpoa_tpu.obs.top import render_frame
    from abpoa_tpu.parallel import scheduler
    M.reset_registry()
    scheduler.reset()
    abpt = _params(device="jax", lockstep="on")
    scheduler.observe_noop_fraction(0.5)
    route = scheduler.plan_route(abpt, 4)
    assert route.kind == "lockstep"
    text = M.registry().render()
    assert not M.lint_exposition(text), M.lint_exposition(text)
    samples, types = M.parse_exposition(text)
    assert M.sample_value(samples, "abpoa_scheduler_routes_total",
                          route="lockstep", reason="eligible") == 1
    assert M.sample_value(samples, "abpoa_lockstep_noop_fraction") == 0.5
    assert M.sample_value(samples, "abpoa_scheduler_route",
                          route="lockstep") == 1
    assert M.sample_value(samples, "abpoa_scheduler_k_cap") == route.k_cap
    frame = render_frame(samples, types, "test.prom", 0.0)
    assert "sched" in frame and "route lockstep" in frame
    assert "noop 0.50" in frame
    scheduler.reset()


def test_scheduler_lane_occupancy_feeds_k_cap():
    """Measured lane occupancy replaces the reactive noop EWMA: one gauge
    (`abpoa_lockstep_lane_occupancy`), and the same K-cap feedback path
    (noop = 1 - occupancy) caps the next groups."""
    from abpoa_tpu.obs import metrics as M
    from abpoa_tpu.parallel import scheduler
    M.reset_registry()
    scheduler.reset()
    abpt = _params(device="jax", lockstep="on")
    r_full = scheduler.plan_route(abpt, 8)
    scheduler.observe_lane_occupancy(0.4)
    assert scheduler.occupancy_ewma() == pytest.approx(0.4)
    assert scheduler.noop_ewma() == pytest.approx(0.6)
    scheduler.observe_lane_occupancy(0.4)
    r_capped = scheduler.plan_route(abpt, 8)
    assert r_capped.k_cap < r_full.k_cap
    text = M.registry().render()
    assert not M.lint_exposition(text), M.lint_exposition(text)
    samples, _types = M.parse_exposition(text)
    assert M.sample_value(
        samples, "abpoa_lockstep_lane_occupancy") == pytest.approx(0.4)
    # the run-mean (churn_gate's A/B estimator) weights every round equally
    # where the EWMA chases the tail: after 0.4, 0.4, 1.0 the EWMA has
    # recovered to 0.7 but the mean reads the whole run's 0.6
    scheduler.observe_lane_occupancy(1.0)
    assert scheduler.occupancy_ewma() == pytest.approx(0.7)
    assert scheduler.occupancy_mean() == pytest.approx(0.6)
    scheduler.reset()
    assert scheduler.occupancy_mean() == pytest.approx(1.0)


def test_scheduler_qlen_crossover(monkeypatch):
    """Satellite 1: a 500 bp serve batch routes SERIAL below the measured
    ~1.5 kb crossover even with lockstep on; ABPOA_TPU_LOCKSTEP_MIN_QLEN
    overrides (0 disables the gate)."""
    from abpoa_tpu.parallel import scheduler
    scheduler.reset()
    abpt = _params(device="jax", lockstep="on")
    r = scheduler.plan_route(abpt, 4, serve=True, qlen=500)
    assert r.kind == "serial" and "crossover" in r.reason
    r = scheduler.plan_route(abpt, 4, serve=True, qlen=2000)
    assert r.kind == "lockstep" and r.impl == "split"
    # unknown qlen -> no gate (batch runner reads whole files up front)
    r = scheduler.plan_route(abpt, 4, serve=True)
    assert r.kind == "lockstep"
    monkeypatch.setenv("ABPOA_TPU_LOCKSTEP_MIN_QLEN", "0")
    r = scheduler.plan_route(abpt, 4, serve=True, qlen=500)
    assert r.kind == "lockstep"
    monkeypatch.setenv("ABPOA_TPU_LOCKSTEP_MIN_QLEN", "300")
    r = scheduler.plan_route(abpt, 4, serve=True, qlen=250)
    assert r.kind == "serial"
    scheduler.reset()


# --------------------------------------------------------------------- #
# vectorized host table build (round 16)                                #
# --------------------------------------------------------------------- #

def _reference_lockstep_tables(g, abpt, query, Qp):
    """The pre-round-16 per-row loop build, kept verbatim as the parity
    reference for the vectorized batch build in dp_chunk."""
    from abpoa_tpu import constants as C
    from abpoa_tpu.align.dp_chunk import P_FLOOR
    from abpoa_tpu.compile.buckets import bucket_pow2 as _bucket_pow2
    if not g.is_topological_sorted:
        g.topological_sort(abpt)
    n = g.node_n
    qlen = len(query)
    nodes = g.nodes
    idx2nid = g.index_to_node_id
    n2i = g.node_id_to_index
    remain = g.node_id_to_max_remain
    pre_lists, out_lists, d_max = [], [], 1
    for i in range(n):
        nd = nodes[int(idx2nid[i])]
        pl = [int(n2i[p]) for p in nd.in_ids] if 0 < i < n else []
        ol = [int(n2i[o]) for o in nd.out_ids] if 0 < i < n - 1 else []
        pre_lists.append(pl)
        out_lists.append(ol)
        d_max = max(d_max, len(pl), len(ol))
    P = max(P_FLOOR, _bucket_pow2(d_max))
    base_r = np.zeros(n, np.int32)
    pre_idx = np.zeros((n, P), np.int32)
    pre_msk = np.zeros((n, P), bool)
    out_idx = np.zeros((n, P), np.int32)
    out_msk = np.zeros((n, P), bool)
    row_active = np.zeros(n, bool)
    remain_rows = np.zeros(n, np.int32)
    for i in range(n):
        nd = nodes[int(idx2nid[i])]
        base_r[i] = nd.base
        remain_rows[i] = remain[int(idx2nid[i])]
        pl = pre_lists[i]
        pre_idx[i, :len(pl)] = pl
        pre_msk[i, :len(pl)] = True
        ol = out_lists[i]
        out_idx[i, :len(ol)] = ol
        out_msk[i, :len(ol)] = True
        row_active[i] = 0 < i < n - 1
    mpl0 = np.full(n, n, np.int32)
    mpl0[0] = 0
    mpr0 = np.zeros(n, np.int32)
    src_rows = [int(n2i[o]) for o in nodes[C.SRC_NODE_ID].out_ids]
    mpl0[src_rows] = 1
    mpr0[src_rows] = 1
    w = abpt.wb + int(abpt.wf * qlen)
    remain_end = int(remain[C.SINK_NODE_ID])
    if abpt.align_mode == C.LOCAL_MODE:
        dp_end0 = qlen
    else:
        r0 = qlen - (int(remain_rows[0]) - remain_end - 1)
        dp_end0 = min(qlen, max(int(mpr0[0]), r0) + w)
    qp = np.zeros((abpt.m, Qp), np.int32)
    query_pad = np.zeros(Qp, np.int32)
    if qlen:
        qp[:, 1: qlen + 1] = abpt.mat[:, query]
        query_pad[:qlen] = query
    return dict(base_r=base_r, pre_idx=pre_idx, pre_msk=pre_msk,
                out_idx=out_idx, out_msk=out_msk, row_active=row_active,
                remain_rows=remain_rows, mpl0=mpl0, mpr0=mpr0, qp=qp,
                query=query_pad, n_rows=n, qlen=qlen, w=w,
                remain_end=remain_end, dp_end0=dp_end0)


def test_build_lockstep_tables_vectorized_parity():
    """The round-16 numpy batch build of the per-round host tables is
    field-for-field identical to the per-row loop it replaced, on real
    POA graphs at every incremental read count (branchy mid-progress
    graphs, not just the final one)."""
    from abpoa_tpu.align.dp_chunk import build_lockstep_tables
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.pipeline import Abpoa, _ingest_records, poa
    for fn in ("test.fa", "heter.fa"):
        abpt = _params(device="numpy")
        seqs, weights = _ingest_records(
            Abpoa(), abpt, read_fastx(os.path.join(DATA_DIR, fn)))
        for j in range(1, len(seqs)):
            ab = Abpoa()
            for r in seqs[:j]:
                ab.append_read(seq="x" * len(r))
            poa(ab, abpt, seqs[:j], weights[:j], 0)
            q = seqs[j]
            Qp = len(q) + 9
            got = build_lockstep_tables(ab.graph, abpt, q, Qp)
            want = _reference_lockstep_tables(ab.graph, abpt, q, Qp)
            assert set(got) == set(want)
            for key in want:
                g_v, w_v = got[key], want[key]
                if isinstance(w_v, np.ndarray):
                    assert g_v.shape == w_v.shape, key
                    assert g_v.dtype == w_v.dtype, key
                    assert np.array_equal(g_v, w_v), (fn, j, key)
                else:
                    assert g_v == w_v, (fn, j, key)


def test_run_dp_chunk_warmable():
    """The new ladder entry warms: the quick-tier anchor precompiles the
    (R, K) grid the CI micro-run hits, through the same dispatch helper
    the driver uses."""
    from abpoa_tpu.compile.ladder import LADDER, QUICK_TIER
    assert "run_dp_chunk" in LADDER
    assert any(a.entry == "run_dp_chunk" for a in QUICK_TIER)
