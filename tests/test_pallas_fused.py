"""Parity tests for the fused-loop Pallas banded kernel (pallas_fused.py).

CPU tests run the kernel in interpret mode (memory-space placement is not
validated there — only semantics); the on-chip test runs compiled in a
subprocess when a real accelerator is reachable and is skipped otherwise.
"""
import io
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tests.conftest import DATA_DIR  # noqa: E402


# Each interpret-mode parity case runs in its own subprocess: the XLA CPU
# compiler deterministically segfaulted under accumulated in-process compile
# state in full-suite order (round-3 finding), and one compiler crash must
# fail one test, not vaporize the pytest process. Children inherit the
# persistent compilation cache (conftest) so reruns stay fast.
_PARITY_CHILD = """
import io, sys
import numpy as np
sys.path.insert(0, {root!r})
{prelude}
import abpoa_tpu.align.fused_loop as fl
if {force_int32}:
    fl.int16_score_limit = lambda abpt: -1
{int16_guard}
from abpoa_tpu.params import Params
from abpoa_tpu.io.fastx import read_fastx
from abpoa_tpu.cons.consensus import generate_consensus
from abpoa_tpu.io.output import output_fx_consensus

def cons(use_pallas):
    abpt = Params(); abpt.device = 'pallas'
    for k, v in {gap_kw!r}.items():
        setattr(abpt, k, v)
    abpt.finalize()
    recs = read_fastx({path!r})
    enc = abpt.char_to_code
    seqs = [enc[np.frombuffer(r.seq.encode(), dtype=np.uint8)].astype(np.uint8)
            for r in recs]
    wgts = [np.ones(len(s), dtype=np.int64) for s in seqs]
    pg, _, _ = fl.progressive_poa_fused(seqs, wgts, abpt,
                                        use_pallas=use_pallas)
    c = generate_consensus(pg, abpt, len(recs))
    out = io.StringIO(); output_fx_consensus(c, abpt, out)
    return out.getvalue()

assert cons(True) == cons(False), 'pallas parity mismatch'
print('PARITY-OK')
"""

# the int16 on-chip runs only prove something while the test data still fits
# the int16 promotion bound; guard the parametrization inside the child
_INT16_GUARD = """
from abpoa_tpu.io.fastx import read_fastx as _rf
from abpoa_tpu.params import Params as _P
_abpt = _P()
_abpt.device = 'numpy'  # pin BEFORE finalize: device='auto' resolution would
                        # init jax in-process and pin the child to CPU
for k, v in {gap_kw!r}.items():
    setattr(_abpt, k, v)
_abpt.finalize()
_qmax = max(len(r.seq) for r in _rf({path!r}))
assert fl.max_score_bound(_abpt, _qmax, 2) <= fl.int16_score_limit(_abpt), \\
    'seq.fa no longer selects int16 planes; int16 coverage lost'
"""


def _parity_child_code(fname, gap_kw, force_int32, pin_cpu, int16_guard=False):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(DATA_DIR, fname)
    return _PARITY_CHILD.format(
        root=root, path=path, gap_kw=gap_kw, force_int32=force_int32,
        prelude=("import jax; jax.config.update('jax_platforms', 'cpu')"
                 if pin_cpu else ""),
        int16_guard=(_INT16_GUARD.format(gap_kw=gap_kw, path=path)
                     if int16_guard else ""))


def _parity_subproc(fname, gap_kw, force_int32):
    code = _parity_child_code(fname, gap_kw, force_int32, pin_cpu=True)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=1800)
    assert "PARITY-OK" in proc.stdout, (
        f"child rc={proc.returncode}\n{proc.stderr[-2000:]}")


@pytest.mark.parametrize("fname", [
    "test.fa", "seq.fa",
    pytest.param("heter.fa", marks=pytest.mark.slow),
])
def test_pallas_fused_matches_scan_int32(fname):
    """int32 planes (post-promotion regime), convex gap."""
    _parity_subproc(fname, {}, True)


@pytest.mark.parametrize("gap_kw", [
    {},                                  # convex (default)
    {"gap_open2": 0},                    # affine
    {"gap_open1": 0, "gap_open2": 0},    # linear
], ids=["convex", "affine", "linear"])
def test_pallas_fused_matches_scan_int16(gap_kw):
    """int16 planes (the natural width for short reads — the reference's
    preferred regime, abpoa_align_simd.c:1293-1302) across all gap modes."""
    _parity_subproc("seq.fa", gap_kw, False)


@pytest.mark.parametrize("gap_kw", [
    {"gap_open2": 0},
    {"gap_open1": 0, "gap_open2": 0},
], ids=["affine", "linear"])
def test_pallas_fused_matches_scan_int32_regimes(gap_kw):
    """Affine/linear with int32 planes."""
    _parity_subproc("seq.fa", gap_kw, True)


def _device_env():
    """Env for on-chip child processes: conftest pins JAX_PLATFORMS=cpu for
    the in-process suite, and children inherit it — which would silently pin
    the 'compiled on chip' children to CPU (and make the reachability probe
    always answer cpu, auto-skipping every on-chip test even with a live
    accelerator). Strip the pin so the real platform wins in children."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    return env


def _accelerator_reachable():
    # answered by the probe launched at collection time (conftest): a cold
    # suite on a wedged tunnel no longer burns the 90 s timeout here
    from tests.conftest import accelerator_reachable
    return accelerator_reachable()


@pytest.mark.parametrize("plane16", [False, True], ids=["int32", "int16"])
@pytest.mark.parametrize("gap_kw", [
    {},                                  # convex (default)
    {"gap_open2": 0},                    # affine
    {"gap_open1": 0, "gap_open2": 0},    # linear
], ids=["convex", "affine", "linear"])
def test_pallas_fused_compiled_on_chip(plane16, gap_kw):
    """Compiled (non-interpret) parity on the real accelerator for every
    kernel variant (both plane widths x all gap regimes), isolated in a
    subprocess with a timeout so a wedged device cannot hang the suite."""
    if not _accelerator_reachable():
        pytest.skip("no accelerator reachable (wedged tunnel or CPU-only)")
    code = _parity_child_code("seq.fa", gap_kw, force_int32=not plane16,
                              pin_cpu=False, int16_guard=plane16)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=900, env=_device_env())
    assert "PARITY-OK" in proc.stdout, proc.stderr[-2000:]


@pytest.mark.parametrize("extra", [
    {"align_mode": 2},
    {"align_mode": 2, "zdrop": 20},
], ids=["extend", "extend-zdrop"])
def test_pallas_fused_matches_scan_extend(extra):
    """Extend mode (+Z-drop) through the Pallas kernel: best-cell/Z-drop
    bookkeeping lives in SMEM scalars (set_extend_max_score,
    abpoa_align_simd.c:1076-1090); parity vs the XLA scan."""
    _parity_subproc("seq.fa", extra, True)


def test_pallas_fused_extend_compiled_on_chip():
    """Compiled extend+Z-drop parity on the real accelerator (the SMEM
    best-state variant must lower on Mosaic, not just in interpret mode)."""
    if not _accelerator_reachable():
        pytest.skip("no accelerator reachable (wedged tunnel or CPU-only)")
    code = _parity_child_code("seq.fa", {"align_mode": 2, "zdrop": 20},
                              force_int32=True, pin_cpu=False)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=900, env=_device_env())
    assert "PARITY-OK" in proc.stdout, proc.stderr[-2000:]


def test_pallas_fused_matches_scan_local():
    """Local mode (-m1) through the Pallas kernel: full-width rows, 0-clamped
    cells, best-anywhere cell tracked in the SMEM scalars; parity vs the XLA
    scan."""
    _parity_subproc("seq.fa", {"align_mode": 1}, True)


def test_pallas_fused_local_compiled_on_chip():
    """Compiled local-mode parity on the real accelerator (the full-width
    band + SMEM best-state variant must lower on Mosaic)."""
    if not _accelerator_reachable():
        pytest.skip("no accelerator reachable (wedged tunnel or CPU-only)")
    code = _parity_child_code("seq.fa", {"align_mode": 1},
                              force_int32=True, pin_cpu=False)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=900, env=_device_env())
    assert "PARITY-OK" in proc.stdout, proc.stderr[-2000:]


# local mode past the VMEM ring budget: the HBM-resident kernel (plane
# outputs double as row history, per-row DMA of predecessor rows)
_LOCAL_HBM_CHILD = """
import sys, numpy as np
sys.path.insert(0, {root!r})
{prelude}
import jax
import abpoa_tpu.align.fused_loop as FL
from abpoa_tpu.align.pallas_fused import fits_vmem, fits_vmem_local_hbm
from abpoa_tpu.params import Params
from abpoa_tpu.cons.consensus import generate_consensus

abpt = Params(); abpt.device = 'pallas'; abpt.align_mode = 1
abpt.finalize()
rng = np.random.default_rng(3)
L = {L}
ref = rng.integers(0, 4, L).astype(np.uint8)
reads = [ref.copy()]
for _ in range(2):
    r = ref.copy(); m = rng.integers(0, L, max(4, L // 50))
    r[m] = (r[m] + 1) % 4
    reads.append(r)
w = [np.ones(len(q), dtype=np.int64) for q in reads]
Qp, W, _ = FL._plan_buckets(abpt, L)
assert not fits_vmem(W, abpt.gap_mode, False, m=abpt.m, Qp=Qp), \\
    'case no longer exceeds the ring budget; raise L'
assert fits_vmem_local_hbm(W, abpt.gap_mode, False, m=abpt.m, Qp=Qp)
pg1, _, _ = FL.progressive_poa_fused(reads, w, abpt, use_pallas=True)
pg2, _, _ = FL.progressive_poa_fused(reads, w, abpt, use_pallas=False)
c1 = generate_consensus(pg1, abpt, len(reads))
c2 = generate_consensus(pg2, abpt, len(reads))
assert c1.cons_base == c2.cons_base and c1.cons_cov == c2.cons_cov
print('PARITY-OK')
"""


@pytest.mark.slow
def test_pallas_fused_local_hbm_matches_scan():
    """Local mode at a width past the VMEM ring budget routes to the
    HBM-resident kernel (pallas_fused_dp_local_hbm) and byte-matches the
    scan (VERDICT r4 task 4). 1.8 kb reads: W=2048 already exceeds the
    3-ring budget, same code path as 10 kb at a suite-friendly cost."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = _LOCAL_HBM_CHILD.format(
        root=root, L=1800,
        prelude="import jax; jax.config.update('jax_platforms', 'cpu')")
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=1800)
    assert "PARITY-OK" in proc.stdout, (
        f"child rc={proc.returncode}\n{proc.stderr[-2000:]}")


def test_pallas_fused_local_hbm_compiled_on_chip():
    """Compiled HBM-resident local kernel on the real accelerator at the
    north-star read length (10 kb): the manual-DMA kernel must lower on
    Mosaic, not just in interpret mode."""
    if not _accelerator_reachable():
        pytest.skip("no accelerator reachable (wedged tunnel or CPU-only)")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = _LOCAL_HBM_CHILD.format(root=root, L=10000, prelude="")
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=2400, env=_device_env())
    assert "PARITY-OK" in proc.stdout, proc.stderr[-2000:]
