"""A wedged accelerator tunnel must never hang the CLI device path.

VERDICT round-2 item 6: the reference's runtime dispatch cannot hang
(src/abpoa_dispatch_simd.c:56-78); our `--device jax` must probe the backend
out-of-process and fall back to the host kernel when the probe times out.
ABPOA_TPU_TEST_WEDGE makes the probe child block forever, simulating the
wedge without needing one.
"""
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tests.conftest import DATA_DIR  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(DATA_DIR))


def _run_cli_wedged(device):
    env = dict(os.environ)
    env["ABPOA_TPU_TEST_WEDGE"] = "1"       # probe child sleeps forever
    env["ABPOA_TPU_PROBE_TIMEOUT"] = "3"    # probe gives up fast
    env.pop("ABPOA_TPU_SKIP_PROBE", None)
    path = os.path.join(DATA_DIR, "seq.fa")
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "abpoa_tpu.cli", "--device", device, path],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=REPO_ROOT)
    return proc, time.time() - t0


def test_cli_wedged_tunnel_falls_back_to_host():
    proc, wall = _run_cli_wedged("jax")
    assert proc.returncode == 0, proc.stderr[-500:]
    assert "probe timed out" in proc.stderr
    # byte-identical to the host run
    env = dict(os.environ)
    env.pop("ABPOA_TPU_TEST_WEDGE", None)
    path = os.path.join(DATA_DIR, "seq.fa")
    want = subprocess.run(
        [sys.executable, "-m", "abpoa_tpu.cli", "--device", "native", path],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=REPO_ROOT)
    assert proc.stdout == want.stdout
    # "within seconds": well under the old behavior (indefinite hang); the
    # bound is loose to tolerate loaded CI hosts
    assert wall < 60


def test_probe_cache_and_reset():
    from abpoa_tpu.utils import probe
    prior = os.environ.get("ABPOA_TPU_SKIP_PROBE")
    probe.reset_probe_cache()
    os.environ["ABPOA_TPU_SKIP_PROBE"] = "1"
    try:
        assert probe.jax_backend_reachable() is True
    finally:
        # restore exactly (conftest sets "1" for the whole session; deleting
        # it would make every later test pay the real subprocess probe)
        if prior is None:
            del os.environ["ABPOA_TPU_SKIP_PROBE"]
        else:
            os.environ["ABPOA_TPU_SKIP_PROBE"] = prior
    probe.reset_probe_cache()


def test_auto_device_resolves_concrete():
    """device="auto" must resolve to a concrete engine at finalize():
    the reference picks the fastest ISA at startup
    (src/abpoa_dispatch_simd.c:59-82); on this CPU-pinned session the pick
    is the native C++ kernel (or numpy when g++ is absent), never the
    accelerator and never the literal "auto"."""
    from abpoa_tpu.params import Params
    from abpoa_tpu.utils import probe
    p = Params().finalize()
    assert p.device in ("native", "numpy")
    assert probe.has_accelerator() is False  # conftest pins JAX_PLATFORMS=cpu


def test_pinned_device_survives_finalize():
    from abpoa_tpu.params import Params
    for name in ("numpy", "jax", "pallas"):
        p = Params()
        p.device = name
        assert p.finalize().device == name
