"""Multi-device tests on the 8-way virtual CPU mesh (see conftest)."""
import io
import os

import numpy as np
import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def test_run_batch_multidevice():
    import jax
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch

    assert len(jax.devices()) == 8
    abpt = Params()
    abpt.device = "jax"
    abpt.finalize()
    out = io.StringIO()
    files = [os.path.join(DATA_DIR, "test.fa"), os.path.join(DATA_DIR, "test.fa")]
    run_batch(files, abpt, out)
    text = out.getvalue()
    assert text.count(">Consensus_sequence") == 2


def test_shard_dp_batch_8way():
    import jax
    import jax.numpy as jnp
    from abpoa_tpu.parallel import shard_dp_batch

    mesh, step = shard_dp_batch(8)
    import __graft_entry__ as ge
    args, _gap_mode = ge._real_read_tables()
    # args[10] is the fused-kernel row count; _dp_scan takes the 11 scalars after
    arrays, scalars = args[:10], jnp.stack([jnp.int32(a) for a in args[11:]])
    stacked = [jnp.broadcast_to(jnp.asarray(a)[None], (8,) + jnp.asarray(a).shape)
               for a in arrays]
    stacked.append(jnp.broadcast_to(scalars[None], (8,) + scalars.shape))
    out = step(*stacked)
    out.block_until_ready()
    assert out.shape[0] == 8


def test_graft_dryrun():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


def test_run_batch_8_sets_matches_sequential(tmp_path):
    """-l batch mode over the 8-device mesh: 8 distinct read sets, each
    device-processed set byte-matches the host-sequential result (the
    reference's file-list mode, src/abpoa.c:148-168)."""
    import subprocess
    import sys
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    files = []
    for s in range(8):
        p = str(tmp_path / f"set{s}.fa")
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "make_sim.py"),
             "--ref-len", "200", "--n-reads", "6", "--err", "0.1",
             "--seed", str(100 + s), "--out", p], check=True)
        files.append(p)

    abpt = Params()
    abpt.device = "jax"
    abpt.finalize()
    out = io.StringIO()
    run_batch(files, abpt, out)

    want = io.StringIO()
    abpt2 = Params()
    abpt2.device = "numpy"
    abpt2.finalize()
    for i, fn in enumerate(files):
        abpt2.batch_index = i + 1
        msa_from_file(Abpoa(), abpt2, fn, want)
    assert out.getvalue() == want.getvalue()
