"""Multi-device tests on the 8-way virtual CPU mesh (see conftest)."""
import io
import os

import numpy as np
import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def test_run_batch_multidevice():
    import jax
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch

    assert len(jax.devices()) == 8
    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"  # CPU-only host: lockstep is opt-in (round 9)
    abpt.finalize()
    out = io.StringIO()
    files = [os.path.join(DATA_DIR, "test.fa"), os.path.join(DATA_DIR, "test.fa")]
    run_batch(files, abpt, out)
    text = out.getvalue()
    assert text.count(">Consensus_sequence") == 2


def test_shard_dp_batch_8way():
    import jax
    import jax.numpy as jnp
    from abpoa_tpu.parallel import shard_dp_batch

    mesh, step = shard_dp_batch(8)
    import __graft_entry__ as ge
    args, _gap_mode = ge._real_read_tables()
    # args[10] is the fused-kernel row count; _dp_scan takes the 11 scalars after
    arrays, scalars = args[:10], jnp.stack([jnp.int32(a) for a in args[11:]])
    stacked = [jnp.broadcast_to(jnp.asarray(a)[None], (8,) + jnp.asarray(a).shape)
               for a in arrays]
    stacked.append(jnp.broadcast_to(scalars[None], (8,) + scalars.shape))
    out = step(*stacked)
    out.block_until_ready()
    assert out.shape[0] == 8


@pytest.mark.slow
def test_graft_dryrun():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


@pytest.mark.slow
def test_lockstep_growth_and_parity(tmp_path):
    """Lockstep multi-set batching with forced capacity growth: undersized
    starting buckets make every set trip ERR_NODE_CAP, the host grows the
    batched state and re-enters, and each set's output byte-matches the
    sequential numpy pipeline (VERDICT r4 task 2)."""
    import subprocess
    import sys
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, _ingest_records, msa_from_file, output
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.align import fused_loop as FL

    files = []
    for s in range(4):
        p = str(tmp_path / f"grow{s}.fa")
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "make_sim.py"),
             "--ref-len", "150", "--n-reads", "5", "--err", "0.15",
             "--seed", str(400 + s), "--out", p], check=True)
        files.append(p)

    abpt = Params()
    abpt.device = "jax"
    abpt.finalize()
    sets, wsets, abs_ = [], [], []
    for p in files:
        ab = Abpoa()
        seqs, weights = _ingest_records(ab, abpt, read_fastx(p))
        sets.append(seqs)
        wsets.append(weights)
        abs_.append(ab)

    calls = []
    orig = FL.run_fused_chunk

    def spy(state, *a, **kw):
        calls.append(state.g.in_ids.shape)
        return orig(state, *a, **kw)

    FL.run_fused_chunk = spy
    try:
        # N=192 holds ~1 read's chain graph; reads 2+ must trigger growth
        outs = FL.progressive_poa_fused_batch(
            sets, wsets, abpt, _initial_caps=(192, 8, 8, 128))
    finally:
        FL.run_fused_chunk = orig
    assert len(calls) >= 2 and calls[-1][0] > calls[0][0], calls
    assert all(o is not None for o in outs)

    abpt2 = Params()
    abpt2.device = "numpy"
    abpt2.finalize()
    for s, p in enumerate(files):
        want = io.StringIO()
        msa_from_file(Abpoa(), abpt2, p, want)
        got = io.StringIO()
        abs_[s].graph = outs[s][0]
        output(abs_[s], abpt2, got)
        assert got.getvalue() == want.getvalue(), f"set {s} diverged"


def test_run_batch_mixed_eligibility(tmp_path):
    """A single-read file (fused-ineligible) between eligible sets takes the
    sequential path; output order and bytes still match pure-sequential."""
    import subprocess
    import sys
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    files = []
    # deliberately different length buckets: the lockstep runner must
    # partition them into same-bucket sub-batches and still emit in order
    for s, rl in enumerate((120, 600)):
        p = str(tmp_path / f"mx{s}.fa")
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "make_sim.py"),
             "--ref-len", str(rl), "--n-reads", "4", "--err", "0.1",
             "--seed", str(500 + s), "--out", p], check=True)
        files.append(p)
    single = str(tmp_path / "single.fa")
    with open(single, "w") as fp:
        fp.write(">only\nACGTACGTACGTACGTACGT\n")
    files.insert(1, single)

    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"  # CPU-only host: lockstep is opt-in (round 9)
    abpt.finalize()
    out = io.StringIO()
    run_batch(files, abpt, out)

    want = io.StringIO()
    abpt2 = Params()
    abpt2.device = "numpy"
    abpt2.finalize()
    for i, fn in enumerate(files):
        abpt2.batch_index = i + 1
        msa_from_file(Abpoa(), abpt2, fn, want)
    assert out.getvalue() == want.getvalue()


@pytest.mark.slow
def test_run_batch_8_sets_matches_sequential(tmp_path):
    """-l batch mode over the 8-device mesh: 8 distinct read sets, each
    device-processed set byte-matches the host-sequential result (the
    reference's file-list mode, src/abpoa.c:148-168)."""
    import subprocess
    import sys
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    files = []
    for s in range(8):
        p = str(tmp_path / f"set{s}.fa")
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "make_sim.py"),
             "--ref-len", "200", "--n-reads", "6", "--err", "0.1",
             "--seed", str(100 + s), "--out", p], check=True)
        files.append(p)

    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"  # CPU-only host: lockstep is opt-in (round 9)
    abpt.finalize()
    out = io.StringIO()
    run_batch(files, abpt, out)

    want = io.StringIO()
    abpt2 = Params()
    abpt2.device = "numpy"
    abpt2.finalize()
    for i, fn in enumerate(files):
        abpt2.batch_index = i + 1
        msa_from_file(Abpoa(), abpt2, fn, want)
    assert out.getvalue() == want.getvalue()
