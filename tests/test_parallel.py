"""Multi-device tests on the 8-way virtual CPU mesh (see conftest), and
the supervised process pool (parallel/pool.py, ISSUE 13)."""
import io
import os

import numpy as np
import pytest

from conftest import DATA_DIR, GOLDEN_DIR


def test_run_batch_multidevice():
    import jax
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch

    assert len(jax.devices()) == 8
    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"  # CPU-only host: lockstep is opt-in (round 9)
    abpt.finalize()
    out = io.StringIO()
    files = [os.path.join(DATA_DIR, "test.fa"), os.path.join(DATA_DIR, "test.fa")]
    run_batch(files, abpt, out)
    text = out.getvalue()
    assert text.count(">Consensus_sequence") == 2


def test_shard_dp_batch_8way():
    import jax
    import jax.numpy as jnp
    from abpoa_tpu.parallel import shard_dp_batch

    mesh, step = shard_dp_batch(8)
    import __graft_entry__ as ge
    args, _gap_mode = ge._real_read_tables()
    # args[10] is the fused-kernel row count; _dp_scan takes the 11 scalars after
    arrays, scalars = args[:10], jnp.stack([jnp.int32(a) for a in args[11:]])
    stacked = [jnp.broadcast_to(jnp.asarray(a)[None], (8,) + jnp.asarray(a).shape)
               for a in arrays]
    stacked.append(jnp.broadcast_to(scalars[None], (8,) + scalars.shape))
    out = step(*stacked)
    out.block_until_ready()
    assert out.shape[0] == 8


@pytest.mark.slow
def test_graft_dryrun():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


@pytest.mark.slow
def test_lockstep_growth_and_parity(tmp_path):
    """Lockstep multi-set batching with forced capacity growth: undersized
    starting buckets make every set trip ERR_NODE_CAP, the host grows the
    batched state and re-enters, and each set's output byte-matches the
    sequential numpy pipeline (VERDICT r4 task 2)."""
    import subprocess
    import sys
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, _ingest_records, msa_from_file, output
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.align import fused_loop as FL

    files = []
    for s in range(4):
        p = str(tmp_path / f"grow{s}.fa")
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "make_sim.py"),
             "--ref-len", "150", "--n-reads", "5", "--err", "0.15",
             "--seed", str(400 + s), "--out", p], check=True)
        files.append(p)

    abpt = Params()
    abpt.device = "jax"
    abpt.finalize()
    sets, wsets, abs_ = [], [], []
    for p in files:
        ab = Abpoa()
        seqs, weights = _ingest_records(ab, abpt, read_fastx(p))
        sets.append(seqs)
        wsets.append(weights)
        abs_.append(ab)

    calls = []
    orig = FL.run_fused_chunk

    def spy(state, *a, **kw):
        calls.append(state.g.in_ids.shape)
        return orig(state, *a, **kw)

    FL.run_fused_chunk = spy
    try:
        # N=192 holds ~1 read's chain graph; reads 2+ must trigger growth
        outs = FL.progressive_poa_fused_batch(
            sets, wsets, abpt, _initial_caps=(192, 8, 8, 128))
    finally:
        FL.run_fused_chunk = orig
    assert len(calls) >= 2 and calls[-1][0] > calls[0][0], calls
    assert all(o is not None for o in outs)

    abpt2 = Params()
    abpt2.device = "numpy"
    abpt2.finalize()
    for s, p in enumerate(files):
        want = io.StringIO()
        msa_from_file(Abpoa(), abpt2, p, want)
        got = io.StringIO()
        abs_[s].graph = outs[s][0]
        output(abs_[s], abpt2, got)
        assert got.getvalue() == want.getvalue(), f"set {s} diverged"


def test_run_batch_mixed_eligibility(tmp_path):
    """A single-read file (fused-ineligible) between eligible sets takes the
    sequential path; output order and bytes still match pure-sequential."""
    import subprocess
    import sys
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    files = []
    # deliberately different length buckets: the lockstep runner must
    # partition them into same-bucket sub-batches and still emit in order
    for s, rl in enumerate((120, 600)):
        p = str(tmp_path / f"mx{s}.fa")
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "make_sim.py"),
             "--ref-len", str(rl), "--n-reads", "4", "--err", "0.1",
             "--seed", str(500 + s), "--out", p], check=True)
        files.append(p)
    single = str(tmp_path / "single.fa")
    with open(single, "w") as fp:
        fp.write(">only\nACGTACGTACGTACGTACGT\n")
    files.insert(1, single)

    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"  # CPU-only host: lockstep is opt-in (round 9)
    abpt.finalize()
    out = io.StringIO()
    run_batch(files, abpt, out)

    want = io.StringIO()
    abpt2 = Params()
    abpt2.device = "numpy"
    abpt2.finalize()
    for i, fn in enumerate(files):
        abpt2.batch_index = i + 1
        msa_from_file(Abpoa(), abpt2, fn, want)
    assert out.getvalue() == want.getvalue()


@pytest.mark.slow
def test_run_batch_8_sets_matches_sequential(tmp_path):
    """-l batch mode over the 8-device mesh: 8 distinct read sets, each
    device-processed set byte-matches the host-sequential result (the
    reference's file-list mode, src/abpoa.c:148-168)."""
    import subprocess
    import sys
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    files = []
    for s in range(8):
        p = str(tmp_path / f"set{s}.fa")
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "make_sim.py"),
             "--ref-len", "200", "--n-reads", "6", "--err", "0.1",
             "--seed", str(100 + s), "--out", p], check=True)
        files.append(p)

    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"  # CPU-only host: lockstep is opt-in (round 9)
    abpt.finalize()
    out = io.StringIO()
    run_batch(files, abpt, out)

    want = io.StringIO()
    abpt2 = Params()
    abpt2.device = "numpy"
    abpt2.finalize()
    for i, fn in enumerate(files):
        abpt2.batch_index = i + 1
        msa_from_file(Abpoa(), abpt2, fn, want)
    assert out.getvalue() == want.getvalue()


# --------------------------------------------------------------------------- #
# supervised process pool (parallel/pool.py, ISSUE 13)                        #
# --------------------------------------------------------------------------- #

def _pool_params(workers):
    from abpoa_tpu.params import Params
    abpt = Params()
    abpt.device = "numpy"   # jax-import-free workers: ~0.5s spawns
    abpt.workers = workers
    return abpt.finalize()


def _sim_files(tmp_path, n, ref_len=120):
    import subprocess
    import sys
    files = []
    for s in range(n):
        p = str(tmp_path / f"pool{s}.fa")
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "make_sim.py"),
             "--ref-len", str(ref_len), "--n-reads", "4", "--err", "0.1",
             "--seed", str(700 + s), "--out", p], check=True)
        files.append(p)
    return files


def test_pool_restart_backoff_schedule():
    """The respawn ladder: immediate first spawn, then base * 2^(n-1)
    capped at 30 s for consecutive deaths."""
    from abpoa_tpu.parallel.pool import restart_backoff_s
    os.environ["ABPOA_TPU_POOL_BACKOFF_S"] = "0.5"
    try:
        assert restart_backoff_s(0) == 0.0
        assert restart_backoff_s(1) == 0.5
        assert restart_backoff_s(2) == 1.0
        assert restart_backoff_s(3) == 2.0
        assert restart_backoff_s(10) == 30.0  # cap
    finally:
        del os.environ["ABPOA_TPU_POOL_BACKOFF_S"]


def test_pool_resolve_workers_precedence(monkeypatch):
    from abpoa_tpu.parallel import resolve_workers
    abpt = _pool_params(0)
    monkeypatch.setenv("ABPOA_TPU_WORKERS", "3")
    assert resolve_workers(abpt, 8) == 3
    assert resolve_workers(abpt, 2) == 2      # never more than sets
    abpt.workers = 5                          # explicit Params wins
    assert resolve_workers(abpt, 8) == 5
    monkeypatch.setenv("ABPOA_TPU_WORKERS", "auto")
    abpt.workers = 0
    assert resolve_workers(abpt, 1) == 1      # single set: no pool


def test_pool_output_byte_identical_across_w(tmp_path):
    """Pool output (W=4) byte-matches the in-process serial runner (W=1)
    over mixed-length sets — the ordering + containment layer must be
    invisible in the bytes."""
    from abpoa_tpu.parallel import run_batch
    files = _sim_files(tmp_path, 4)
    outs = {}
    for w in (1, 4):
        out = io.StringIO()
        stats = run_batch(files, _pool_params(w), out)
        assert stats == {"sets": 4, "quarantined": 0}
        outs[w] = out.getvalue()
    assert outs[1] == outs[4]
    assert outs[1].count(">Consensus_sequence") == 4


def test_pool_double_crash_quarantines_poison_job(tmp_path):
    """worker_sigsegv:2 -> one job crashes its worker twice -> poison
    quarantine; healthy sets complete; exactly one requeue."""
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    from abpoa_tpu.parallel import run_batch
    files = _sim_files(tmp_path, 3)
    obs.start_run()
    rz.inject.configure("worker_sigsegv:2")
    try:
        out = io.StringIO()
        stats = run_batch(files, _pool_params(3), out)
    finally:
        rz.inject.reset()
    assert stats["quarantined"] == 1
    assert out.getvalue().count(">Consensus_sequence") == 2
    c = obs.report().counters
    assert c.get("inject.worker_sigsegv") == 2
    assert c.get("pool.requeues") == 1
    assert c.get("pool.poison_jobs") == 1
    assert c.get("pool.worker_crashes") == 2
    kinds = {r["kind"] for r in obs.report().faults}
    assert "poison_job" in kinds and "worker_crash" in kinds


def test_pool_requeue_exactly_once_and_archive_idempotent(
        tmp_path, monkeypatch):
    """worker_kill:1 -> the killed job retries once on a fresh worker and
    SUCCEEDS; the archive carries exactly ONE record per job (terminal
    status only — requeues never double-append)."""
    import json
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    from abpoa_tpu.parallel import run_batch
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE", "1")
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_DIR", str(tmp_path / "reports"))
    files = _sim_files(tmp_path, 3)
    obs.start_run()
    rz.inject.configure("worker_kill:1")
    try:
        out = io.StringIO()
        stats = run_batch(files, _pool_params(3), out)
    finally:
        rz.inject.reset()
    assert stats["quarantined"] == 0
    assert out.getvalue().count(">Consensus_sequence") == 3
    c = obs.report().counters
    assert c.get("inject.worker_kill") == 1
    assert c.get("pool.requeues") == 1
    assert c.get("pool.restarts", 0) >= 1
    assert not c.get("pool.poison_jobs")
    recs = []
    with open(tmp_path / "reports" / "reports.jsonl") as fp:
        for ln in fp:
            rec = json.loads(ln)
            if rec.get("kind") == "pool_job":
                recs.append(rec)
    assert len(recs) == 3, recs
    assert sorted(r["label"] for r in recs) == sorted(files)
    assert all(r["status"] == "ok" for r in recs)
    # the requeued job records BOTH attempts in its single record
    assert max(r["attempts"] for r in recs) == 2


def test_pool_deadline_hard_kill_is_terminal(tmp_path, monkeypatch):
    """A job that outlives its deadline is SIGKILLed and quarantined
    WITHOUT a retry (the budget is spent — watchdog semantics), while
    fast jobs complete."""
    from abpoa_tpu import obs
    from abpoa_tpu.parallel import run_batch
    files = _sim_files(tmp_path, 2)
    monkeypatch.setenv("ABPOA_TPU_POOL_DELAY_S", "5")
    monkeypatch.setenv("ABPOA_TPU_POOL_DEADLINE_S", "1.0")
    obs.start_run()
    out = io.StringIO()
    stats = run_batch(files, _pool_params(2), out)
    assert stats["quarantined"] == 2
    c = obs.report().counters
    assert c.get("pool.kills") == 2
    assert not c.get("pool.requeues")
    kinds = {r["kind"] for r in obs.report().faults}
    assert "worker_killed" in kinds


def test_pool_rss_budget_kill(tmp_path, monkeypatch):
    """A worker whose resident set exceeds the RSS budget is hard-killed
    on its heartbeat; the job retries once (fresh worker, same breach)
    and lands in poison quarantine."""
    from abpoa_tpu import obs
    from abpoa_tpu.parallel import run_batch
    files = _sim_files(tmp_path, 1)
    monkeypatch.setenv("ABPOA_TPU_POOL_RSS_MB", "8")   # below interpreter RSS
    monkeypatch.setenv("ABPOA_TPU_POOL_HEARTBEAT_S", "0.2")
    monkeypatch.setenv("ABPOA_TPU_POOL_DELAY_S", "3")  # outlive a heartbeat
    obs.start_run()
    out = io.StringIO()
    # workers=2 with one set still builds a 1-slot pool; force 2 jobs
    stats = run_batch(files * 2, _pool_params(2), out)
    c = obs.report().counters
    assert stats["quarantined"] == 2, (stats, c)
    assert c.get("pool.kills", 0) >= 2
    kinds = {r["kind"] for r in obs.report().faults}
    assert "worker_killed" in kinds and "poison_job" in kinds


def test_pool_graceful_drain_on_sigterm(tmp_path):
    """SIGTERM mid-batch: queued jobs cancel, in-flight jobs finish,
    completed output is emitted in order, rc stays 0."""
    import signal
    import subprocess
    import sys
    import time
    files = _sim_files(tmp_path, 4)
    lst = tmp_path / "list.txt"
    lst.write_text("".join(f + "\n" for f in files))
    out = tmp_path / "out.fa"
    env = dict(os.environ, ABPOA_TPU_POOL_DELAY_S="3.0",
               ABPOA_TPU_WORKERS="2")
    proc = subprocess.Popen(
        [sys.executable, "-m", "abpoa_tpu.cli", "-l", str(lst),
         "--device", "numpy", "-o", str(out)],
        env=env, stderr=subprocess.PIPE, text=True)
    # SIGTERM once the FIRST set's output landed: at that point sets 2-3
    # are in flight (2 workers x 3s delay) and set 4 is still queued —
    # deterministic mid-batch, however slow the host is
    t0 = time.time()
    while time.time() - t0 < 40:
        if out.exists() and ">Consensus_sequence" in out.read_text():
            break
        if proc.poll() is not None:
            raise AssertionError("batch finished before the drain signal")
        time.sleep(0.05)
    proc.send_signal(signal.SIGTERM)
    t0 = time.time()
    rc = proc.wait(timeout=30)
    stderr = proc.stderr.read()
    assert rc == 0, stderr
    assert time.time() - t0 < 15
    assert "SIGTERM drain" in stderr, stderr
    n = out.read_text().count(">Consensus_sequence")
    assert 1 <= n <= 4, (n, stderr)


def test_pool_worker_report_merges_to_parent(tmp_path):
    """Counters and fault records produced INSIDE workers surface in the
    parent run report (the one --report/--metrics/archive read)."""
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    from abpoa_tpu.parallel import run_batch
    files = _sim_files(tmp_path, 3)
    obs.start_run()
    rz.inject.configure("poison_set:1")
    try:
        out = io.StringIO()
        stats = run_batch(files, _pool_params(3), out)
    finally:
        rz.inject.reset()
    # the leased shot fired in exactly ONE worker (not re-armed per
    # process) and came back as parent-report state
    assert stats["quarantined"] == 1
    c = obs.report().counters
    assert c.get("inject.poison_set") == 1
    assert c.get("quarantine.sets") == 1
    kinds = {r["kind"] for r in obs.report().faults}
    assert "poisoned_set" in kinds


def test_pool_kill_shots_rebind_after_poison(tmp_path):
    """worker_sigsegv:3 — the bound victim absorbs two shots and is
    poisoned; the THIRD shot rebinds to another job, which survives its
    single crash via the exactly-once requeue (shots never strand).
    Single-worker pool: with parallel slots the later jobs could finish
    before the rebind, which is healthy but not what this test pins."""
    from abpoa_tpu import obs
    from abpoa_tpu import resilience as rz
    from abpoa_tpu.parallel import run_batch
    files = _sim_files(tmp_path, 3)
    obs.start_run()
    rz.inject.configure("worker_sigsegv:3")
    try:
        out = io.StringIO()
        from abpoa_tpu.parallel.pool import run_pool_batch
        stats = run_pool_batch(files, _pool_params(1), out, 1)
    finally:
        rz.inject.reset()
    assert stats["quarantined"] == 1
    assert out.getvalue().count(">Consensus_sequence") == 2
    c = obs.report().counters
    assert c.get("inject.worker_sigsegv") == 3, c
    assert c.get("pool.poison_jobs") == 1, c
    assert c.get("pool.requeues") == 2, c
