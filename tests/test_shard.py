"""Sharded-route tests (PR 19, ROADMAP item 2a).

- mesh plumbing units: the ABPOA_TPU_MESH/--mesh request grammar, the
  virtual-CPU-mesh XLA flag rewrite, mesh_size, and shard_dp_round's
  shape guards
- scheduler: the `sharded` route (consensus + map flavours), its
  mesh x per-chip K cap, and the per-route occupancy/noop isolation
  regression (the map stream's ~1.0 occupancy must not launder the
  consensus drain out of the lockstep cap)
- promoted multichip dryrun phases (__graft_entry__.dryrun_multichip
  keeps running them end-to-end; these are the pytest-owned versions):
  phase 1 (independent fused read-set alignments shard_vmapped over the
  mesh) and phase 4 (one static graph, the read batch sharded across
  the mesh, byte-equal to the unsharded dispatch AND the host oracle)
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from abpoa_tpu.params import Params  # noqa: E402


def _params(device="jax", **kw):
    abpt = Params()
    abpt.device = device
    for k, v in kw.items():
        setattr(abpt, k, v)
    abpt.finalize()
    return abpt


# --------------------------------------------------------------------- #
# mesh request grammar + virtual mesh pin                               #
# --------------------------------------------------------------------- #

def test_requested_mesh_size_parsing(monkeypatch):
    from abpoa_tpu.parallel.shard import requested_mesh_size
    monkeypatch.delenv("ABPOA_TPU_MESH", raising=False)
    assert requested_mesh_size() == 0
    monkeypatch.setenv("ABPOA_TPU_MESH", "8")
    assert requested_mesh_size() == 8
    monkeypatch.setenv("ABPOA_TPU_MESH", "0")
    assert requested_mesh_size() == 0
    monkeypatch.setenv("ABPOA_TPU_MESH", "garbage")
    assert requested_mesh_size() == 0
    monkeypatch.setenv("ABPOA_TPU_MESH", "-3")
    assert requested_mesh_size() == 0
    # an explicit CLI value wins over the env var
    assert requested_mesh_size(cli=4) == 4
    assert requested_mesh_size(cli=0) == 0


def test_pin_virtual_cpu_mesh_flag_rewrite(monkeypatch):
    """The promoted dryrun pin: max-wins on the existing device-count flag,
    other XLA flags preserved, platform forced to cpu."""
    from abpoa_tpu.parallel.shard import pin_virtual_cpu_mesh
    monkeypatch.setenv(
        "XLA_FLAGS",
        "--xla_cpu_foo=1 --xla_force_host_platform_device_count=4")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    pin_virtual_cpu_mesh(8)
    flags = os.environ["XLA_FLAGS"]
    assert "--xla_cpu_foo=1" in flags
    assert flags.count("--xla_force_host_platform_device_count=") == 1
    assert "--xla_force_host_platform_device_count=8" in flags
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    # idempotent, and an existing LARGER count wins (never shrink a mesh
    # another component already pinned)
    pin_virtual_cpu_mesh(2)
    assert ("--xla_force_host_platform_device_count=8"
            in os.environ["XLA_FLAGS"])


def test_mesh_size_and_discovery():
    from abpoa_tpu.parallel.shard import discover_mesh, mesh_size
    assert mesh_size(None) == 1
    # < 2 is OFF, not a 1-device mesh
    assert discover_mesh(0) is None
    assert discover_mesh(1) is None
    # conftest pins the virtual 8-device CPU mesh before jax init
    mesh = discover_mesh(2)
    assert mesh is not None and mesh_size(mesh) == 2
    assert mesh.axis_names == ("set",)
    with pytest.raises(RuntimeError, match="mesh of 4096 devices"):
        discover_mesh(4096)


def test_shard_dp_round_shape_guards():
    from abpoa_tpu.parallel.shard import discover_mesh, shard_dp_round
    abpt = _params("jax")
    with pytest.raises(ValueError, match="needs a >=2-device mesh"):
        shard_dp_round(abpt, [], 8, 64, 8, 128, 64, True, None)
    mesh = discover_mesh(2)
    with pytest.raises(ValueError, match="not divisible by the mesh"):
        shard_dp_round(abpt, [], 3, 64, 8, 128, 64, True, mesh)


# --------------------------------------------------------------------- #
# scheduler: the sharded route + per-route feedback isolation           #
# --------------------------------------------------------------------- #

@pytest.fixture
def sched_env(monkeypatch):
    from abpoa_tpu.parallel import scheduler
    monkeypatch.delenv("ABPOA_TPU_LOCKSTEP_K", raising=False)
    monkeypatch.delenv("ABPOA_TPU_LOCKSTEP_IMPL", raising=False)
    monkeypatch.setenv("ABPOA_TPU_LOCKSTEP", "1")
    scheduler.reset()
    yield scheduler
    scheduler.reset()


def test_plan_route_sharded_consensus(sched_env, monkeypatch):
    from abpoa_tpu.parallel.scheduler import plan_route
    monkeypatch.setenv("ABPOA_TPU_MESH", "8")
    abpt = _params("jax")
    route = plan_route(abpt, 16)
    assert route.kind == "sharded" and route.impl == "split"
    assert route.workers == 8
    # global K cap prices the whole mesh: mesh x per-chip noop cap (8 x 8)
    assert route.k_cap == 64
    assert "sharded K=64 over mesh=8" in route.reason
    # an explicit mesh=0 argument turns the upgrade off
    route = plan_route(abpt, 16, mesh=0)
    assert route.kind == "lockstep" and route.impl == "split"


def test_plan_route_sharded_map(sched_env, monkeypatch):
    from abpoa_tpu.parallel.scheduler import plan_route
    monkeypatch.setenv("ABPOA_TPU_MESH", "4")
    route = plan_route(_params("jax"), 32, workload="map")
    assert route.kind == "sharded" and route.impl == "map"
    assert route.workers == 4 and route.k_cap == 32
    # no batched DP backend -> the mesh request cannot shard anything
    route = plan_route(_params("numpy"), 32, workload="map")
    assert route.kind == "serial"


def test_sharded_k_cap_rides_its_own_noop(sched_env, monkeypatch):
    """Sharded divergence feedback halves the PER-CHIP cap, scaled by the
    mesh — and reads only the sharded route's own EWMA."""
    from abpoa_tpu.parallel import scheduler
    monkeypatch.setenv("ABPOA_TPU_MESH", "8")
    scheduler.observe_lane_occupancy(0.4, route="sharded")
    route = scheduler.plan_route(_params("jax"), 16)
    assert route.kind == "sharded"
    # noop ewma 0.6 -> 8 // 2 // 2 = 2 per chip, x 8 mesh
    assert route.k_cap == 8 * 2


def test_per_route_occupancy_isolation(sched_env):
    """Small-fix regression (PR 19): the map stream's by-construction
    ~1.0 occupancy must not feed the lockstep/sharded K-cap EWMAs, and a
    divergent consensus drain must not starve the map cap."""
    from abpoa_tpu.parallel import scheduler as s
    for _ in range(6):
        s.observe_lane_occupancy(1.0, route="map")
    s.observe_lane_occupancy(0.25, route="lockstep")
    assert s.occupancy_ewma("map") == pytest.approx(1.0)
    assert s.occupancy_ewma("lockstep") == pytest.approx(0.25)
    assert s.occupancy_ewma("sharded") == pytest.approx(1.0)  # untouched
    # lockstep cap halves on ITS noop (0.75 -> three halvings of 8)
    assert s.noop_k_cap(8, route="lockstep") == 1
    # map cap stays wide open despite the lockstep drain
    assert s.noop_k_cap(8, route="map") == 8
    assert s.noop_k_cap(8, route="sharded") == 8
    # the pooled mean still sees every observation (gate A/B estimator)
    assert s.occupancy_mean() == pytest.approx((6 * 1.0 + 0.25) / 7)
    assert s.occupancy_mean("lockstep") == pytest.approx(0.25)


# --------------------------------------------------------------------- #
# promoted dryrun phase 1: fused read sets shard_vmapped over the mesh  #
# --------------------------------------------------------------------- #

@pytest.mark.slow
def test_fused_sets_shard_vmap():
    """__graft_entry__ dryrun phase 1, pytest-owned: every mesh slot runs
    the single-dispatch fused progressive-POA loop on its own read set
    inside one jitted shard_map step; all sets must consume every read
    with zero error flags."""
    import jax
    import jax.numpy as jnp
    from abpoa_tpu.align.fused_loop import init_fused_state, run_fused_chunk
    from abpoa_tpu.align.oracle import dp_inf_min
    from abpoa_tpu.parallel.shard import discover_mesh, shard_vmap

    mesh = discover_mesh(2)
    abpt = _params("jax")
    S, R, L, Qp = 2, 4, 96, 128
    N, E, A, W = 512, 8, 8, 128
    rng = np.random.default_rng(0)
    ref = rng.integers(0, 4, (S, L))
    seqs = np.zeros((S, R, Qp), dtype=np.int32)
    lens = np.zeros((S, R), dtype=np.int32)
    for s in range(S):
        for r in range(R):
            read = []
            for b in ref[s]:
                x = rng.random()
                if x < 0.03:
                    read.append((int(b) + int(rng.integers(1, 4))) % 4)
                elif x < 0.05:
                    read.append(int(b))
                    read.append(int(rng.integers(0, 4)))
                elif x < 0.07:
                    pass
                else:
                    read.append(int(b))
            read = np.array(read[: Qp - 2], dtype=np.int32)
            seqs[s, r, : len(read)] = read
            lens[s, r] = len(read)
    wgts = np.ones((S, R, Qp), dtype=np.int32)
    mat = np.ascontiguousarray(abpt.mat.astype(np.int32))
    qp = np.zeros((S, R, abpt.m, Qp), dtype=np.int32)
    for s in range(S):
        for r in range(R):
            ln = int(lens[s, r])
            qp[s, r, :, 1: ln + 1] = mat[:, seqs[s, r, :ln]]
    inf_min = dp_inf_min(abpt)
    mat_d = jnp.asarray(mat)

    def one_set(seqs_pad, wgts_pad, lens_set, qp_set):
        st = init_fused_state(N, E, A, n_reads=R, Pcap=Qp + 2)
        st = run_fused_chunk(
            st, seqs_pad, wgts_pad, lens_set, jnp.int32(R),
            qp_set, mat_d, jnp.int32(abpt.wb), jnp.float32(abpt.wf),
            jnp.int32(inf_min),
            jnp.int32(abpt.gap_open1), jnp.int32(abpt.gap_ext1),
            jnp.int32(abpt.gap_oe1), jnp.int32(abpt.gap_open2),
            jnp.int32(abpt.gap_ext2), jnp.int32(abpt.gap_oe2),
            gap_mode=abpt.gap_mode, W=W, max_ops=N + Qp + 8,
            gap_on_right=bool(abpt.put_gap_on_right),
            put_gap_at_end=bool(abpt.put_gap_at_end))
        return jnp.stack([st.read_idx, st.err, st.g.node_n])

    @jax.jit
    def step(a, b, c, d):
        return shard_vmap(one_set, mesh, 4)(a, b, c, d)

    out = np.asarray(step(jnp.asarray(seqs), jnp.asarray(wgts),
                          jnp.asarray(lens), jnp.asarray(qp)))
    assert (out[:, 0] == R).all(), f"unconsumed reads: {out[:, 0]}"
    assert (out[:, 1] == 0).all(), f"error flags: {out[:, 1]}"
    assert (out[:, 2] > 2).all()


# --------------------------------------------------------------------- #
# promoted dryrun phase 4: map-batch sharding on one static graph       #
# --------------------------------------------------------------------- #

def _static_graph_and_reads(n_base=4, n_reads=4, L=96, seed=11):
    from abpoa_tpu.align.dp_chunk import StaticGraphTables
    from abpoa_tpu.pipeline import Abpoa, poa
    rng = np.random.default_rng(seed)
    ref = rng.integers(0, 4, L).astype(np.uint8)
    abpt = _params("jax")
    ab = Abpoa()
    base_reads = []
    for _ in range(n_base):
        r = ref.copy()
        muts = rng.integers(0, L, 3)
        r[muts] = (r[muts] + 1) % 4
        base_reads.append(r)
    for q in base_reads:
        ab.append_read(seq="x" * len(q))
    poa(ab, abpt, base_reads,
        [np.ones(len(q), dtype=np.int64) for q in base_reads], 0)
    reads = []
    for _ in range(n_reads):
        r = ref.copy()
        muts = rng.integers(0, L, 5)
        r[muts] = (r[muts] + 1) % 4
        reads.append(r)
    return abpt, ab.graph, StaticGraphTables(ab.graph, abpt), reads


@pytest.mark.slow
def test_shard_dp_round_matches_unsharded_and_oracle():
    """__graft_entry__ dryrun phase 4, pytest-owned: the graph tables
    replicate into every shard while the read batch shards across the
    mesh. The sharded round's packed rows must be byte-identical to the
    unsharded dispatch, and every lane's GAF record must byte-match the
    per-read host oracle."""
    from abpoa_tpu.align.dp_chunk import (chunk_plane16, dispatch_dp_chunk,
                                          result_from_chunk)
    from abpoa_tpu.compile.ladder import plan_chunk_buckets, qp_rung
    from abpoa_tpu.io.gaf import gaf_record
    from abpoa_tpu.parallel.map_driver import map_read_host
    from abpoa_tpu.parallel.shard import discover_mesh, shard_dp_round

    mesh = discover_mesh(2)
    abpt, g, static, reads = _static_graph_and_reads()
    Qp = qp_rung(max(len(q) for q in reads))
    _qp, W, _local = plan_chunk_buckets(abpt, Qp - 2)
    R, P = static.R, static.P
    plane16 = chunk_plane16(abpt, Qp - 2, static.n_rows)
    stamped = [static.tables_for(q, Qp) for q in reads]
    Kb = 4
    sharded = shard_dp_round(abpt, stamped, Kb, R, P, Qp, W, plane16, mesh)
    unsharded = dispatch_dp_chunk(abpt, stamped, Kb, R, P, Qp, W, plane16)
    assert sharded.dtype == unsharded.dtype
    assert np.array_equal(sharded, unsharded), \
        "sharded round diverged from the unsharded dispatch"
    for k, q in enumerate(reads):
        res, flags = result_from_chunk(abpt, sharded[k], stamped[k],
                                       static.idx2nid)
        assert not flags["overflow"] and not flags["bt_err"], \
            f"lane {k} flags {flags}"
        want_r, want_s = map_read_host(g, abpt, q)
        got = gaf_record(f"r{k}", q, res, static.base_by_nid, strand="+")
        want = gaf_record(f"r{k}", q, want_r, static.base_by_nid,
                          strand=want_s)
        assert got == want, f"lane {k} GAF diverged"


@pytest.mark.slow
def test_shard_dp_round_partial_fill_padding():
    """k_real < Kb: padding lanes are born finished and land in the
    trailing shards; live rows still byte-match the unsharded dispatch."""
    from abpoa_tpu.align.dp_chunk import chunk_plane16, dispatch_dp_chunk
    from abpoa_tpu.compile.ladder import plan_chunk_buckets, qp_rung
    from abpoa_tpu.parallel.shard import discover_mesh, shard_dp_round

    mesh = discover_mesh(2)
    abpt, _g, static, reads = _static_graph_and_reads(n_reads=3)
    Qp = qp_rung(max(len(q) for q in reads))
    _qp, W, _local = plan_chunk_buckets(abpt, Qp - 2)
    plane16 = chunk_plane16(abpt, Qp - 2, static.n_rows)
    stamped = [static.tables_for(q, Qp) for q in reads]
    sharded = shard_dp_round(abpt, stamped, 4, static.R, static.P, Qp, W,
                             plane16, mesh)
    unsharded = dispatch_dp_chunk(abpt, stamped, 4, static.R, static.P,
                                  Qp, W, plane16)
    assert sharded.shape[0] == 3
    assert np.array_equal(sharded, unsharded)
