"""Fleet-grade metrics subsystem tests (ISSUE 10 tentpole + satellites).

- sketch honesty: p50/p95/p99 from the streaming log-bucket sketch agree
  with exact nearest-rank percentiles within the declared tolerance on a
  1M-record stream — 10x the old READS_CAP, where the capped-list path
  used to lie
- exporter golden: stable metric names/labels, exposition parses and
  lints clean, atomic textfile writes, the stdlib HTTP endpoint serves
  the same bytes
- archive: one JSONL record per run, size-bounded rotation, windows span
  the rotation boundary
- slo: rc 0 on a healthy window, rc 1 once an injected violation spends
  an objective's error budget, rc 2 with nothing to evaluate
- `top --once` renders a frame from a live exporter file
- `report --diff` compares two run reports field by field
- probe-log bounding (utils/probe.py): the JSONL log keeps the newest N
- overhead guard: metrics publication on vs off stays within noise of
  the obs guard (host-side dict/array updates only, no device syncs)
"""
import io
import json
import os
import random
import subprocess
import sys
import time
import urllib.request

import pytest

from conftest import DATA_DIR

SIM2K = os.path.join(DATA_DIR, "sim2k.fa")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _native_or_skip():
    from abpoa_tpu.native import load
    if load() is None:
        pytest.skip("native host core unavailable (no C++ toolchain)")


# --------------------------------------------------------------------- #
# sketch                                                                #
# --------------------------------------------------------------------- #

def test_sketch_percentiles_honest_at_10x_reads_cap():
    """Acceptance: stream 1M synthetic per-read records (10x READS_CAP)
    through record_read; sketch p50/p95/p99 match exact nearest-rank
    percentiles within the declared relative error, while the raw-record
    list stays capped."""
    import importlib
    R = importlib.import_module("abpoa_tpu.obs.report")
    from abpoa_tpu.obs.metrics import LogSketch
    rng = random.Random(7)
    n = 10 * R.READS_CAP
    vals = [rng.lognormvariate(-5.5, 1.3) for _ in range(n)]
    rep = R.RunReport()
    rec = rep.record_read
    for v in vals:
        rec(v, 100, 50, "native")
    blk = rep._reads_block()
    assert blk["count"] == n
    assert blk["records_kept"] == R.READS_CAP
    assert blk["dropped"] == n - R.READS_CAP
    exact = sorted(vals)
    tol = LogSketch.RELATIVE_ERROR
    for q, key in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
        ref = 1e3 * R.exact_percentile(exact, q)
        est = blk["wall_ms"][key]
        assert est == pytest.approx(ref, rel=tol), (key, est, ref)
    # the capped-list path would have answered the percentile of the
    # FIRST 100k records only; verify the sketch didn't
    assert blk["wall_ms"]["max"] == pytest.approx(1e3 * exact[-1])


def test_sketch_merge_and_bounds():
    from abpoa_tpu.obs.metrics import LogSketch
    a, b = LogSketch(), LogSketch()
    rng = random.Random(3)
    va = [rng.uniform(1e-4, 1e-1) for _ in range(5000)]
    vb = [rng.uniform(1e-3, 1.0) for _ in range(5000)]
    for v in va:
        a.observe(v)
    for v in vb:
        b.observe(v)
    a.merge(b)
    assert a.count == 10000
    exact = sorted(va + vb)
    import math
    for q in (0.5, 0.95, 0.99):
        ref = exact[max(0, math.ceil(q * len(exact)) - 1)]
        assert a.quantile(q) == pytest.approx(ref, rel=a.RELATIVE_ERROR)
    # memory bound: the bucket array never grows
    assert len(a.counts) == LogSketch.N_BUCKETS
    # out-of-range values clamp into the edge buckets (quantiles answer
    # from there); exact min/max are preserved alongside
    s = LogSketch()
    s.observe(1e-9)
    s.observe(1e6)
    assert s.count == 2 and s.min == 1e-9 and s.max == 1e6
    assert s.quantile(0.01) <= LogSketch.LO * LogSketch.GROWTH
    assert s.quantile(1.0) >= LogSketch.HI / LogSketch.GROWTH


def test_merge_expositions_quantiles_match_pooled_sketch():
    """The fleet rollup contract (round 16): render N per-replica
    expositions, merge the TEXTS, and the merged histogram's quantiles
    must match the pooled-observation sketch within the declared
    LogSketch tolerance — the exposition round-trip loses nothing the
    tolerance doesn't already allow. Counters/gauges sum; quantile
    gauges are recomputed, not summed."""
    from abpoa_tpu.obs import metrics as M
    rng = random.Random(16)
    pooled = M.LogSketch()
    texts = []
    for rep in range(3):
        reg = M.MetricsRegistry()
        h = reg.histogram("abpoa_serve_request_seconds", "latency")
        # replicas see different latency regimes (the realistic case:
        # one slow replica skews the fleet tail)
        lo, hi = (1e-3, 1e-1) if rep < 2 else (5e-2, 2.0)
        for _ in range(4000):
            v = rng.uniform(lo, hi)
            h.observe(v)
            pooled.observe(v)
        reg.counter("abpoa_serve_requests_total", "req").inc(
            100 + rep, status="ok")
        reg.gauge("abpoa_serve_queue_depth", "depth").set(rep + 1)
        texts.append(reg.render())
    merged = M.merge_expositions(texts)
    assert not M.lint_exposition(merged), M.lint_exposition(merged)
    samples, types = M.parse_exposition(merged)
    assert types["abpoa_serve_request_seconds"] == "histogram"
    # counters and gauges summed per label set
    assert M.sample_value(samples, "abpoa_serve_requests_total",
                          status="ok") == 303
    assert M.sample_value(samples, "abpoa_serve_queue_depth") == 6
    # merged quantiles vs the pooled sketch, within declared tolerance
    sk = M.sketch_from_exposition(samples, "abpoa_serve_request_seconds")
    assert sk.count == pooled.count == 12000
    assert sk.sum == pytest.approx(pooled.sum, rel=1e-9)
    for q in (0.5, 0.95, 0.99):
        assert sk.quantile(q) == pytest.approx(
            pooled.quantile(q), rel=M.LogSketch.RELATIVE_ERROR)
        # the recomputed quantile gauge agrees with the merged sketch
        gq = M.sample_value(samples,
                            "abpoa_serve_request_seconds_quantile",
                            quantile=str(q))
        assert gq == pytest.approx(sk.quantile(q), rel=1e-6)
    # merging a merged exposition is a no-op (idempotent rollup)
    again, _ = M.parse_exposition(M.merge_expositions([merged]))
    sk2 = M.sketch_from_exposition(again, "abpoa_serve_request_seconds")
    assert sk2.counts == sk.counts and sk2.count == sk.count


# --------------------------------------------------------------------- #
# exporter                                                              #
# --------------------------------------------------------------------- #

def test_exporter_golden_names_and_lint(tmp_path):
    """The exposition of a real (numpy) run carries the stable family
    names with their expected labels, parses, and lints clean."""
    from abpoa_tpu import obs
    from abpoa_tpu.obs import metrics as M
    M.reset_registry()
    from abpoa_tpu.pyapi import msa_aligner
    a = msa_aligner(device="numpy")
    a.msa(["ACGTACGTAA", "ACGTACGTA", "ACGTTCGTAA"], True, False)
    path = str(tmp_path / "m.prom")
    M.write_textfile(path)
    with open(path) as fp:
        text = fp.read()
    assert M.lint_exposition(text) == []
    samples, types = M.parse_exposition(text)
    # goldened family names (renaming any of these is a breaking change
    # for dashboards/alerts)
    expected = {
        "abpoa_runs_total": "counter",
        "abpoa_reads_total": "counter",
        "abpoa_read_wall_seconds": "histogram",
        "abpoa_read_wall_seconds_quantile": "gauge",
        "abpoa_phase_wall_seconds_total": "counter",
        "abpoa_dispatches_total": "counter",
        "abpoa_dp_cells_total": "counter",
        "abpoa_dp_cell_ops_total": "counter",
        "abpoa_dp_dispatches_total": "counter",
        "abpoa_reads_per_second": "gauge",
        "abpoa_cell_updates_per_second": "gauge",
        "abpoa_trace_dropped_events": "gauge",
    }
    for fam, typ in expected.items():
        assert types.get(fam) == typ, (fam, types.get(fam))
    assert M.sample_value(samples, "abpoa_reads_total", backend="numpy") == 3
    assert M.sample_value(samples, "abpoa_dispatches_total",
                          backend="numpy") == 2
    for q in ("0.5", "0.95", "0.99"):
        assert M.sample_value(samples, "abpoa_read_wall_seconds_quantile",
                              quantile=q) > 0
    phases = {dict(lb).get("phase") for (n, lb) in samples
              if n == "abpoa_phase_wall_seconds_total"}
    assert {"align", "fusion", "consensus"} <= phases


def test_textfile_exporter_flusher_and_http(tmp_path):
    """start/stop of the periodic exporter (atomic writes) and the
    stdlib HTTP endpoint serving the same exposition."""
    from abpoa_tpu.obs import metrics as M
    reg = M.reset_registry()
    reg.counter("abpoa_runs_total", "Runs started").inc(1)
    path = str(tmp_path / "live.prom")
    M.start_textfile_exporter(path, interval_s=0.05)
    try:
        time.sleep(0.2)
    finally:
        M.stop_textfile_exporter()
    with open(path) as fp:
        text = fp.read()
    assert M.lint_exposition(text) == []
    assert "abpoa_runs_total 1" in text
    # no torn-write droppings left behind
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []
    srv = M.start_http_exporter(0)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            body = resp.read().decode()
            assert resp.status == 200
            assert "text/plain" in resp.headers["Content-Type"]
        assert M.lint_exposition(body) == []
        assert "abpoa_runs_total 1" in body
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10) as resp:
            pytest.fail("404 expected")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    finally:
        srv.shutdown()


def test_breaker_gauge_flips_and_resets():
    """resilience publication: the breaker-state gauge reads 1 while a
    backend is demoted and 0 again once the next run resets it."""
    from abpoa_tpu.obs import metrics as M
    from abpoa_tpu.resilience.breaker import breaker
    M.reset_registry()
    br = breaker()
    br.reset()
    for _ in range(3):
        br.record_failure("jax", "oom")
    assert br.is_open("jax")
    s, _ = M.parse_exposition(M.registry().render())
    assert M.sample_value(s, "abpoa_breaker_open", backend="jax") == 1
    br.reset()
    s, _ = M.parse_exposition(M.registry().render())
    assert M.sample_value(s, "abpoa_breaker_open", backend="jax") == 0


def test_batch_progress_gauges():
    """run_batch and msa_batch publish sets/done gauges, the live
    progress `top` renders during a -l batch."""
    import io
    from abpoa_tpu.obs import metrics as M
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import run_batch
    M.reset_registry()
    abpt = Params()
    abpt.device = "numpy"
    abpt.finalize()
    out = io.StringIO()
    stats = run_batch([os.path.join(DATA_DIR, "test.fa")] * 3, abpt, out)
    assert stats["sets"] == 3
    s, _ = M.parse_exposition(M.registry().render())
    assert M.sample_value(s, "abpoa_batch_sets") == 3
    assert M.sample_value(s, "abpoa_batch_sets_done") == 3
    from abpoa_tpu.pyapi import msa_aligner
    a = msa_aligner(device="numpy")
    # one poisoned set (empty sequence): quarantined sets still count as
    # done — the batch moved past them (same semantics as the -l runner)
    res = a.msa_batch([["ACGTACGT", "ACGTACG"], [""],
                       ["TTTTCCCC", "TTTTCCC"]], True, False)
    assert res[1] is None and res[0] is not None and res[2] is not None
    s, _ = M.parse_exposition(M.registry().render())
    assert M.sample_value(s, "abpoa_batch_sets") == 3
    assert M.sample_value(s, "abpoa_batch_sets_done") == 3
    # a later non-batch run zeroes the run-scoped gauges instead of
    # exporting stale progress
    a.msa(["ACGTACGT", "ACGTACG"], True, False)
    s, _ = M.parse_exposition(M.registry().render())
    assert M.sample_value(s, "abpoa_batch_sets") == 0
    assert M.sample_value(s, "abpoa_batch_sets_done") == 0


# --------------------------------------------------------------------- #
# archive + slo                                                         #
# --------------------------------------------------------------------- #

def _fake_report(p99_ms=5.0, reads=20, fallbacks=0, misses=0, faults=0):
    rep = {"schema_version": 4, "created": "2026-08-04T00:00:00Z",
           "total_wall_s": 1.0,
           "counters": {"dp.cells": 1000},
           "reads": {"count": reads,
                     "fallbacks": {"x": fallbacks} if fallbacks else {},
                     "wall_ms": {"p50": 1.0, "p95": 3.0, "p99": p99_ms,
                                 "mean": 1.5, "max": p99_ms}},
           "compiles": ({"hits": 4, "misses": misses} if misses else None),
           "degraded": None, "mfu": None}
    rep["faults"] = {"count": faults} if faults else None
    return rep


def test_archive_append_and_rotation(tmp_path, monkeypatch):
    from abpoa_tpu.obs import archive
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE", "1")
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_DIR", str(tmp_path))
    # tiny rotation bound (~10 records of ~330 B): 12 appends rotate once
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_MAX_MB", "0.003")  # 3000 bytes
    for i in range(12):
        p = archive.append_report(_fake_report(p99_ms=float(i)),
                                  label=f"run{i}", device="numpy")
        assert p is not None
    live = archive.archive_path()
    assert os.path.exists(live + ".1"), "rotation never happened"
    # bounded: live + one rotated generation, never unbounded growth
    live_size = os.path.getsize(live) if os.path.exists(live) else 0
    assert live_size <= 2 * 3000
    assert os.path.getsize(live + ".1") <= 2 * 3000
    # windows span the rotation boundary, oldest-first, newest retained
    win = archive.read_window(6)
    assert [r["label"] for r in win] == [f"run{i}" for i in range(6, 12)]
    assert win[-1]["read_wall_ms"]["p99"] == 11.0
    # disabled archiving writes nothing
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE", "0")
    assert archive.append_report(_fake_report()) is None


def test_archive_concurrent_writers_never_tear_lines(tmp_path, monkeypatch):
    """ISSUE 12 satellite: `abpoa-tpu serve` worker threads append one
    archive record per request while rotation is racing them. Every line
    in both generations must parse as a complete record — O_APPEND
    single-write appends and locked rotation, no interleaving, no torn
    tails."""
    import threading
    from abpoa_tpu.obs import archive
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE", "1")
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_DIR", str(tmp_path))
    # tiny bound so the writers force many rotations mid-storm
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_MAX_MB", "0.002")  # 2000 bytes
    n_threads, n_each = 8, 60
    errors = []

    def writer(tid):
        try:
            for i in range(n_each):
                # distinctive payload so a torn/interleaved line cannot
                # accidentally parse back into a valid record
                rec = {"kind": "serve_request", "label": f"t{tid}-r{i}",
                       "marker": "x" * 40, "reads": i, "faults": 0}
                assert archive.append_record(rec) is not None
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(f"t{tid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    live = archive.archive_path()
    lines = []
    for p in (live, live + ".1"):
        if os.path.exists(p):
            with open(p) as fp:
                lines.extend(fp.read().splitlines())
    assert lines, "nothing archived"
    labels = set()
    for ln in lines:
        rec = json.loads(ln)  # EVERY archived line parses
        assert rec["marker"] == "x" * 40
        assert rec["label"] not in labels, f"duplicate {rec['label']}"
        labels.add(rec["label"])
    # rotation drops whole old generations, never corrupts the survivors:
    # the live + one rotated file hold an uninterleaved suffix of writes
    assert len(labels) == len(lines)
    # read_window parses the same storm without raising
    win = archive.read_window(0)
    assert all(r.get("marker") == "x" * 40 for r in win)


def test_slo_rc_flips_on_injected_violation(tmp_path, monkeypatch):
    """Acceptance: `abpoa-tpu slo` exits 0 on a healthy window and
    nonzero once injected p99 violations exhaust the error budget."""
    from abpoa_tpu.obs import archive
    from abpoa_tpu.obs.slo import slo_main
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE", "1")
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_DIR", str(tmp_path / "arch"))
    objectives = {
        "window_runs": 50,
        "objectives": [
            {"name": "read-p99-wall", "metric": "read_p99_ms",
             "max": 100.0, "error_budget": 0.10},
            {"name": "fault-rate", "metric": "fault_rate",
             "max": 0.0, "error_budget": 0.10},
        ]}
    obj = str(tmp_path / "obj.json")
    with open(obj, "w") as fp:
        json.dump(objectives, fp)
    # empty archive: nothing to evaluate -> rc 2
    assert slo_main(["--objectives", obj, "-q"]) == 2
    for _ in range(20):
        archive.append_report(_fake_report(p99_ms=5.0))
    assert slo_main(["--objectives", obj, "-q"]) == 0
    # one bad run out of 21 (~4.8%) stays inside the 10% budget
    archive.append_report(_fake_report(p99_ms=5000.0))
    assert slo_main(["--objectives", obj, "-q"]) == 0
    # two more bad runs (3/23 = 13%) spend the budget -> rc 1
    archive.append_report(_fake_report(p99_ms=5000.0))
    archive.append_report(_fake_report(p99_ms=5000.0))
    out = str(tmp_path / "slo.json")
    assert slo_main(["--objectives", obj, "--json", out, "-q"]) == 1
    with open(out) as fp:
        res = json.load(fp)
    byname = {o["name"]: o for o in res["objectives"]}
    assert byname["read-p99-wall"]["violated"] is True
    assert byname["read-p99-wall"]["bad"] == 3
    assert byname["read-p99-wall"]["burn_rate"] > 1.0
    assert byname["fault-rate"]["violated"] is False
    assert res["violated"] is True


def test_cli_run_archives_and_slo_end_to_end(tmp_path, monkeypatch):
    """A real CLI run (numpy) archives its report; `abpoa-tpu slo`
    evaluates the shipped tools/slo_objectives.json against it."""
    from abpoa_tpu.cli import main
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE", "1")
    monkeypatch.setenv("ABPOA_TPU_ARCHIVE_DIR", str(tmp_path / "arch"))
    rc = main([os.path.join(DATA_DIR, "test.fa"), "--device", "numpy",
               "-o", str(tmp_path / "c.fa")])
    assert rc == 0
    from abpoa_tpu.obs import archive
    win = archive.read_window(10)
    assert len(win) == 1 and win[0]["reads"] == 4
    assert main(["slo", "-q"]) == 0


# --------------------------------------------------------------------- #
# top + diff                                                            #
# --------------------------------------------------------------------- #

def test_top_once_renders_frame(tmp_path, capsys):
    from abpoa_tpu.cli import main
    from abpoa_tpu.obs import metrics as M
    reg = M.reset_registry()
    reg.counter("abpoa_runs_total", "Runs started").inc(2)
    reg.counter("abpoa_reads_total", "reads").inc(40, backend="jax")
    reg.counter("abpoa_phase_wall_seconds_total",
                "phase walls").inc(3.0, phase="align_fused")
    reg.counter("abpoa_phase_wall_seconds_total",
                "phase walls").inc(1.0, phase="consensus")
    reg.counter("abpoa_compile_misses_total", "misses").inc(1)
    reg.gauge("abpoa_breaker_open", "breaker").set(1, backend="pallas")
    path = str(tmp_path / "m.prom")
    M.write_textfile(path)
    assert main(["top", path, "--once"]) == 0
    out = capsys.readouterr().out
    assert "runs 2" in out
    assert "align_fused" in out and "75.0%" in out
    assert "pallas=OPEN" in out
    assert "compiles 1 compiled" in out
    # missing file: a waiting frame, not a crash
    assert main(["top", str(tmp_path / "absent.prom"), "--once"]) == 0
    assert "waiting for" in capsys.readouterr().out


def test_report_diff(tmp_path, capsys):
    """`abpoa-tpu report --diff A B` renders per-field delta + percent
    change for two real run reports."""
    from abpoa_tpu.cli import main
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    for path in (a, b):
        rc = main([os.path.join(DATA_DIR, "test.fa"), "--device", "numpy",
                   "-o", str(tmp_path / "c.fa"), "--report", path])
        assert rc == 0
    assert main(["report", "--diff", a, b]) == 0
    out = capsys.readouterr().out
    assert "report diff:" in out
    for field in ("total_wall_s", "reads_per_sec", "read_p99_ms",
                  "phase.align_s", "dp_cells"):
        assert field in out
    assert main(["report", "--diff", a]) == 2


# --------------------------------------------------------------------- #
# probe-log bounding                                                    #
# --------------------------------------------------------------------- #

def test_probe_log_bounded(tmp_path):
    from abpoa_tpu.utils.probe import append_jsonl_bounded
    path = str(tmp_path / "probe.jsonl")
    for i in range(230):
        append_jsonl_bounded(path, {"i": i}, max_entries=100)
    with open(path) as fp:
        lines = fp.read().splitlines()
    assert len(lines) == 100
    assert json.loads(lines[0]) == {"i": 130}   # newest kept, oldest gone
    assert json.loads(lines[-1]) == {"i": 229}
    # unwritable path: swallowed, never raises
    append_jsonl_bounded(os.path.join(str(tmp_path), "no", "dir.jsonl"),
                         {"x": 1})


# --------------------------------------------------------------------- #
# overhead guard                                                        #
# --------------------------------------------------------------------- #

def test_metrics_overhead_guard_sim2k():
    """Metric publication must be free (same contract as the PR 6 obs
    guard): warm sim2k wall with the registry mirror enabled stays within
    noise of disabled — every publication is a host-side dict/array
    update, never a device sync."""
    _native_or_skip()
    from abpoa_tpu.obs import metrics as M
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    def run_once():
        abpt = Params()
        abpt.device = "native"
        abpt.finalize()
        t0 = time.perf_counter()
        msa_from_file(Abpoa(), abpt, SIM2K, io.StringIO())
        return time.perf_counter() - t0

    run_once()  # warm
    try:
        M.set_enabled(True)
        on = min(run_once() for _ in range(2))
        M.set_enabled(False)
        off = min(run_once() for _ in range(2))
    finally:
        M.set_enabled(True)
    assert on <= off * 1.25 + 0.05, (on, off)
