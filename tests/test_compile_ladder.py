"""Round-8 compile/shape-management tests (abpoa_tpu/compile).

- single definition site for the bucket math (jax_backend / fused_loop /
  pallas_backend all consume compile/buckets.py);
- ladder property: every rung the planners can request is a declared rung
  (no silent off-ladder compiles), including the growth chains;
- partition_by_length_bucket keys on the same rung function as the chunk
  planner (they can never disagree);
- AOT round-trip: `lower().compile()` executable produces bit-identical
  output to the jit path on one fused chunk;
- recompile budget: after warming, a run reports compiles.misses == 0 and
  fused.recompiles == 0; a fresh process after `warm` loads the rungs
  from the persistent cache (persistent_cache_hit records);
- perf_gate's compile_misses_max budget actually flips the exit status.
"""
import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from conftest import DATA_DIR

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _params(device="jax", align_mode=None):
    from abpoa_tpu.params import Params
    abpt = Params()
    abpt.device = device
    if align_mode is not None:
        abpt.align_mode = align_mode
    return abpt.finalize()


def _tiny_set(n=6, length=110, seed=0):
    rng = np.random.default_rng(seed)
    base = "".join(rng.choice(list("ACGT"), length))
    out = []
    for i in range(n):
        j = 10 + 7 * i
        out.append(base[:j] + "ACGT"[i % 4] + base[j:])
    return out


def _write_fa(path, seqs):
    with open(path, "w") as fp:
        for i, s in enumerate(seqs):
            fp.write(f">r{i}\n{s}\n")


# --------------------------------------------------------------------------- #
# ladder / bucket math (host-only, fast)                                      #
# --------------------------------------------------------------------------- #

def test_bucket_single_definition_site():
    from abpoa_tpu.compile import buckets
    from abpoa_tpu.align import fused_loop, jax_backend, pallas_backend
    assert jax_backend._bucket is buckets.bucket
    assert jax_backend._bucket_pow2 is buckets.bucket_pow2
    assert fused_loop._bucket is buckets.bucket
    assert fused_loop._bucket_pow2 is buckets.bucket_pow2
    assert pallas_backend._bucket is buckets.bucket
    assert pallas_backend._bucket_pow2 is buckets.bucket_pow2


def test_chains_are_exactly_the_bucket_fn():
    """The declared rung chains are the closure of the rounding functions:
    snap(n, chain) == bucket(n, step) for every n up to the caps."""
    from abpoa_tpu.compile.buckets import bucket, bucket_pow2, snap
    from abpoa_tpu.compile.ladder import GEOM_64, GEOM_128, GEOM_1024, POW2
    rng = np.random.default_rng(8)
    for n in [1, 2, 127, 128, 129, 1000, 12345] + list(
            rng.integers(1, 200_000, 200)):
        n = int(n)
        assert snap(n, GEOM_128) == bucket(n, 128)
        assert snap(n, GEOM_64) == bucket(n, 64)
        assert snap(n, GEOM_1024) == bucket(n, 1024)
        assert snap(n, POW2) == bucket_pow2(n)


def test_planner_requests_are_on_ladder():
    """Property: every shape the fused-chunk planner can request — start
    buckets AND six growth rungs of the node-capacity chain — is a
    declared rung of its axis."""
    from abpoa_tpu.align import fused_loop as FL
    from abpoa_tpu.compile.buckets import bucket, grow_node_cap
    from abpoa_tpu.compile.ladder import (POW2, POW2_128, POW2_READS,
                                          k_rung, on_ladder, reads_rung)
    abpt = _params("numpy")
    from abpoa_tpu import constants as C
    abpt_local = _params("numpy", align_mode=C.LOCAL_MODE)
    rng = np.random.default_rng(7)
    qmaxes = [1, 50, 126, 127, 2000, 2014, 2015, 9999] + [
        int(x) for x in rng.integers(1, 60_000, 120)]
    for qmax in qmaxes:
        for ab in (abpt, abpt_local):
            Qp, W, _ = FL._plan_buckets(ab, qmax)
            assert on_ladder("run_fused_chunk", "Qp", Qp), (qmax, Qp)
            assert W in POW2_128, (qmax, W)
        N = bucket(2 * (qmax + 2) + 64, 1024)
        for _ in range(6):
            assert on_ladder("run_fused_chunk", "N", N), (qmax, N)
            N = grow_node_cap(N)
    for n in [1, 2, 7, 8, 20, 500, 1000]:
        assert reads_rung(n) in POW2_READS
        assert k_rung(n) in POW2
        assert k_rung(n, 8) % 8 == 0


def test_sharded_planner_requests_are_on_ladder():
    """Property (PR 19): every sharded bucket axis `plan_route` + the
    sharded dispatch can request — per-shard K halvings under the noop
    cap, mesh widths, the row/degree growth rungs, Qp/W — is a declared
    rung of the run_dp_chunk[sharded] ladder entry, so `warm` can always
    precompile what a sharded run will dispatch."""
    from abpoa_tpu.align.dp_chunk import plan_degree_rung, plan_row_rung
    from abpoa_tpu.compile.ladder import (k_rung, mesh_rung, on_ladder,
                                          plan_chunk_buckets, qp_rung)
    from abpoa_tpu.parallel import scheduler
    abpt = _params("numpy")
    E = "run_dp_chunk[sharded]"
    rng = np.random.default_rng(19)
    for mesh_n in (2, 4, 8, 16, 64, 256):
        assert on_ladder(E, "mesh", mesh_rung(mesh_n)), mesh_n
        # the scheduler's per-chip cap chain: base 8 halved by the noop
        # EWMA down to the drain floor of 1 lane per shard
        for noop in (0.0, 0.3, 0.6, 0.9, 1.0):
            per_chip = scheduler.noop_k_cap(8, noop=noop, route="sharded")
            assert on_ladder(E, "K", per_chip), (noop, per_chip)
            # pow2 mesh keeps the mesh-divisible global rung's per-shard
            # slice on the declared chain
            kb = k_rung(mesh_n * per_chip, mesh_n)
            assert kb % mesh_n == 0
            assert on_ladder(E, "K", kb // mesh_n), (mesh_n, per_chip, kb)
    for qmax in [60, 300, 2200, 9999] + [
            int(x) for x in rng.integers(1, 60_000, 60)]:
        Qp, W, _ = plan_chunk_buckets(abpt, qmax)
        assert on_ladder(E, "Qp", Qp) and on_ladder(E, "Qp", qp_rung(qmax))
        assert on_ladder(E, "W", W), (qmax, W)
        R = plan_row_rung(qmax + 2)
        stop = plan_row_rung(2 * (qmax + 2) + 64)
        for _ in range(6):
            assert on_ladder(E, "R", R), (qmax, R)
            if R >= stop:
                break
            R = plan_row_rung(R + 1)
    for d in (1, 2, 5, 8, 30):
        assert on_ladder(E, "P", plan_degree_rung(d))


def test_window_planner_on_ladder():
    """The seeded-window batch planner's R/Qp/degree axes are declared."""
    from abpoa_tpu.compile.buckets import bucket, bucket_pow2
    from abpoa_tpu.compile.ladder import on_ladder
    for gn in (1, 63, 64, 65, 500, 9000):
        assert on_ladder("dp_full_batch", "R", bucket(gn, 64))
    for qlen in (0, 100, 2000, 20000):
        assert on_ladder("dp_full_batch", "Qp", bucket(qlen + 1, 128))
    for d in (1, 2, 3, 5, 9):
        assert on_ladder("dp_full_batch", "P", bucket_pow2(d))
        assert on_ladder("dp_full_batch", "B", bucket_pow2(d))


def test_rungs_raise_past_declared_caps():
    """Beyond the declared chain caps the rung helpers RAISE (clear error
    naming the cap) instead of silently producing an off-ladder shape the
    warmer could never precompile."""
    from abpoa_tpu.compile.ladder import (GEOM_128, MESH, POW2_READS,
                                          mesh_rung, qp_rung, reads_rung)
    assert reads_rung(20000) in POW2_READS
    assert qp_rung(200_000) in GEOM_128
    assert mesh_rung(256) in MESH
    with pytest.raises(ValueError, match="beyond the declared ladder cap"):
        reads_rung((1 << 17) + 1)
    with pytest.raises(ValueError, match="beyond the declared ladder cap"):
        qp_rung(1 << 19)
    # a mesh wider than the declared 256-device chain must RAISE, not
    # silently compile an off-ladder mesh shape (PR 19 cap-raise test)
    with pytest.raises(ValueError, match="beyond the declared ladder cap"):
        mesh_rung(512)


def test_qmax_interval_roundtrip():
    from abpoa_tpu.compile.ladder import GEOM_128, qmax_interval, qp_rung
    for rung in GEOM_128[:24]:
        lo, hi = qmax_interval(rung)
        assert qp_rung(lo) == rung
        assert qp_rung(hi) == rung
        assert qp_rung(hi + 1) != rung


def test_partition_keys_match_planner():
    """Lockstep sub-batching and the chunk planner key through the SAME
    rung function: each group's planner Qp equals the group's shared rung
    for every member (the round-8 satellite fix)."""
    from abpoa_tpu.align import fused_loop as FL
    from abpoa_tpu.compile.ladder import qp_rung
    abpt = _params("numpy")
    rng = np.random.default_rng(3)
    entries = []
    for k in range(24):
        lens = rng.integers(40, 4000, size=rng.integers(2, 6))
        entries.append((k, [np.zeros(int(x), np.uint8) for x in lens], None))
    groups = FL.partition_by_length_bucket(entries)
    assert sum(len(g) for g in groups) == len(entries)
    for g in groups:
        group_qmax = max(len(s) for e in g for s in e[1])
        key = qp_rung(group_qmax)
        for e in g:
            qmax = max(len(s) for s in e[1])
            assert qp_rung(qmax) == key
            # the chunk planner agrees with the partition key
            assert FL._plan_buckets(abpt, qmax)[0] == key


def test_warm_anchor_signatures_cover_interval():
    """The warmer enumerates every distinct start signature across the
    anchor's whole Qp-rung interval (the N-start breakpoint inside the
    2 kb rung is the regression this guards)."""
    from abpoa_tpu.align.fused_loop import _fused_anchor_signatures
    from abpoa_tpu.compile.buckets import bucket
    from abpoa_tpu.compile.ladder import WarmAnchor, qmax_interval, qp_rung
    abpt = _params("numpy")
    anchor = WarmAnchor("run_fused_chunk", qmax=2200, n_reads=20, growth=0)
    sigs = _fused_anchor_signatures(abpt, anchor)
    lo, hi = qmax_interval(qp_rung(2200))
    want_N = {bucket(2 * (q + 2) + 64, 1024) for q in range(lo, hi + 1)}
    assert want_N == {s["N"] for s in sigs}


# --------------------------------------------------------------------------- #
# AOT round-trip + recompile budget (device paths, CPU backend)               #
# --------------------------------------------------------------------------- #

def test_aot_lower_compile_bit_identical():
    """jax.jit(...).lower().compile() — the AOT path `abpoa-tpu warm`
    relies on — produces bit-identical output to the jit call on one real
    fused chunk."""
    import jax
    import jax.numpy as jnp
    from abpoa_tpu.align import fused_loop as FL
    from abpoa_tpu.align.oracle import (INT16_MIN, dp_inf_min,
                                        int16_score_limit, max_score_bound)

    abpt = _params("jax")
    seqs = [np.frombuffer(s.encode(), np.uint8) for s in _tiny_set(4, 80)]
    enc = abpt.char_to_code
    seqs = [enc[s].astype(np.uint8) for s in seqs]
    weights = [np.ones(len(s), np.int64) for s in seqs]
    qmax = max(len(s) for s in seqs)
    n_rung = FL.reads_rung(len(seqs))
    Qp, W, local_m = FL._plan_buckets(abpt, qmax)
    N = FL._bucket(2 * (qmax + 2) + 64, 1024)
    E = A = 8
    mat = np.ascontiguousarray(abpt.mat.astype(np.int32))
    seqs_pad, wgts_pad, lens, qp_all = FL._pad_read_set(
        seqs, weights, Qp, mat, abpt.m, n_rows=n_rung)
    int16_limit = int16_score_limit(abpt)
    plane16 = max_score_bound(abpt, qmax, 2) <= int16_limit
    inf_min = dp_inf_min(abpt, INT16_MIN if plane16 else FL.INT32_MIN)
    kwargs = FL._static_chunk_kwargs(
        abpt, W=W, max_ops=N + Qp + 8, plane16=plane16,
        int16_limit=int16_limit, use_pallas=False, pl_interpret=True,
        record_paths=False, amb=False, local_m=local_m)
    args = (FL.init_fused_state(N, E, A), jnp.asarray(seqs_pad),
            jnp.asarray(wgts_pad), jnp.asarray(lens),
            jnp.int32(len(seqs)), jnp.asarray(qp_all), jnp.asarray(mat),
            *FL._scalar_chunk_args(abpt, inf_min))

    out_jit = FL.run_fused_chunk(*args, **kwargs)
    compiled = FL.run_fused_chunk.lower(*args, **kwargs).compile()
    # the AOT executable takes the traced arguments only (statics baked);
    # zdrop is the one traced kwarg in the chunk signature
    out_aot = compiled(*args, zdrop=kwargs["zdrop"])
    assert int(out_jit.err) == 0 and int(out_jit.read_idx) == len(seqs)
    leaves_j = jax.tree.leaves(out_jit)
    leaves_a = jax.tree.leaves(out_aot)
    assert len(leaves_j) == len(leaves_a)
    for lj, la in zip(leaves_j, leaves_a):
        assert np.array_equal(np.asarray(lj), np.asarray(la))


def test_warm_then_run_zero_misses():
    """Recompile-budget regression: after warming the workload's anchor,
    an in-process run reports compiles.misses == 0 and
    fused.recompiles == 0 (the round-7 `compiles` block is the judge)."""
    from abpoa_tpu import obs
    from abpoa_tpu.compile import WarmAnchor, warm_ladder
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    abpt = _params("jax")
    fa = os.path.join("/tmp", "ladder_smoke.fa")
    _write_fa(fa, _tiny_set(6, 110, seed=1))
    obs.start_run()
    summary = warm_ladder(anchors=[
        WarmAnchor("run_fused_chunk", qmax=120, n_reads=6, growth=1)],
        abpt=abpt)
    assert summary["signatures"] >= 2  # start + 1 growth rung

    obs.start_run()
    msa_from_file(Abpoa(), abpt, fa, io.StringIO())
    rep = obs.finalize_report()
    comp = rep.get("compiles")
    assert comp is not None, "device run must produce a compiles block"
    assert comp["misses"] == 0, comp
    assert rep["counters"].get("fused.recompiles", 0) == 0
    # and the run actually used the fused chunk (not a silent fallback)
    assert any(r["fn"] == "run_fused_chunk" for r in comp["records"])


def test_reads_rung_padding_parity():
    """Reads-axis rung padding (new in round 8) must not change a single
    output byte vs the host oracle."""
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    fa = os.path.join("/tmp", "ladder_parity.fa")
    _write_fa(fa, _tiny_set(5, 90, seed=2))  # 5 reads -> rung 8: 3 pad rows
    got, want = io.StringIO(), io.StringIO()
    msa_from_file(Abpoa(), _params("jax"), fa, got)
    msa_from_file(Abpoa(), _params("numpy"), fa, want)
    assert got.getvalue() == want.getvalue()


def test_lockstep_k_rung_padding_parity():
    """K=3 sets (non-pow2) pad to the K=4 rung with born-finished empty
    sets; results match per-set sequential processing exactly."""
    from abpoa_tpu.align import fused_loop as FL
    abpt = _params("jax")
    enc = abpt.char_to_code
    sets, wsets = [], []
    for s in range(3):
        seqs = [enc[np.frombuffer(x.encode(), np.uint8)].astype(np.uint8)
                for x in _tiny_set(4, 70, seed=10 + s)]
        sets.append(seqs)
        wsets.append([np.ones(len(x), np.int64) for x in seqs])
    outs = FL.progressive_poa_fused_batch(sets, wsets, abpt)
    assert len(outs) == 3
    for k in range(3):
        assert outs[k] is not None
        pg_batch = outs[k][0]
        pg_solo, _, _ = FL.progressive_poa_fused(sets[k], wsets[k], abpt)
        assert pg_batch.node_n == pg_solo.node_n
        for a, b in zip(pg_batch.nodes, pg_solo.nodes):
            assert (a.base, a.in_ids, a.out_ids, a.in_w, a.out_w) == \
                (b.base, b.in_ids, b.out_ids, b.in_w, b.out_w)


def test_fresh_process_persistent_cache_hits(tmp_path):
    """`abpoa-tpu warm` then a FRESH process: the run's compiles block
    shows persistent-cache loads, not full XLA compiles (acceptance
    criterion for the cache wiring)."""
    cache = str(tmp_path / "xla")
    env = dict(os.environ, JAX_PLATFORMS="cpu", ABPOA_TPU_SKIP_PROBE="1",
               ABPOA_TPU_XLA_CACHE_DIR=cache)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    fa = str(tmp_path / "pc.fa")
    _write_fa(fa, _tiny_set(6, 110, seed=3))
    anchor = ("from abpoa_tpu.compile import WarmAnchor, warm_ladder\n"
              "from abpoa_tpu.params import Params\n"
              "abpt = Params(); abpt.device = 'jax'; abpt.finalize()\n"
              "s = warm_ladder(anchors=[WarmAnchor('run_fused_chunk', "
              "qmax=120, n_reads=6, growth=0)], abpt=abpt)\n")
    # process 1: warm (compiles, populates the persistent cache)
    p1 = subprocess.run([sys.executable, "-c", anchor + "print('OK')"],
                        capture_output=True, text=True, env=env, cwd=REPO,
                        timeout=600)
    assert p1.returncode == 0, p1.stderr[-2000:]
    # process 2: real run; its fused-chunk compile record must be a
    # persistent-cache load
    code = (
        "import io, json\n"
        "from abpoa_tpu import obs\n"
        "from abpoa_tpu.params import Params\n"
        "from abpoa_tpu.pipeline import Abpoa, msa_from_file\n"
        "abpt = Params(); abpt.device = 'jax'; abpt.finalize()\n"
        "obs.start_run()\n"
        f"msa_from_file(Abpoa(), abpt, {fa!r}, io.StringIO())\n"
        "rep = obs.finalize_report()\n"
        "print('COMPILES ' + json.dumps(rep['compiles']))\n")
    p2 = subprocess.run([sys.executable, "-c", code], capture_output=True,
                        text=True, env=env, cwd=REPO, timeout=600)
    assert p2.returncode == 0, p2.stderr[-2000:]
    comp = json.loads(p2.stdout.split("COMPILES ", 1)[1])
    recs = [r for r in comp["records"] if r["fn"] == "run_fused_chunk"
            and not r["cache_hit"]]
    assert recs, comp
    assert all(r.get("persistent_cache_hit") for r in recs), recs


# --------------------------------------------------------------------------- #
# perf_gate compile budget                                                    #
# --------------------------------------------------------------------------- #

def test_perf_gate_compile_misses_budget_flips(tmp_path):
    """The compile_misses_max budget actually gates: a measurement with
    in-run misses fails against the checked-in budget of 0, passes when
    the budget allows it."""
    with open(os.path.join(REPO, "tools", "perf_baseline.json")) as fp:
        base = json.load(fp)
    assert base.get("compile_misses_max") == 0
    current = dict(base)
    current["compile_misses"] = 3
    cur = str(tmp_path / "cur.json")
    with open(cur, "w") as fp:
        json.dump(current, fp)
    gate = os.path.join(REPO, "tools", "perf_gate.py")
    r = subprocess.run([sys.executable, gate, "--current", cur],
                       capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "compile_misses" in r.stderr
    r = subprocess.run([sys.executable, gate, "--current", cur,
                        "--compile-misses-max", "5"],
                       capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.slow
def test_warm_quick_tier_sim2k_zero_misses():
    """The satellite's literal contract: after `warm --ladder quick`, a
    warm sim2k run reports compiles.misses == 0 and fused.recompiles == 0."""
    from abpoa_tpu import obs
    from abpoa_tpu.compile import warm_ladder
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    abpt = _params("jax")
    obs.start_run()
    warm_ladder(tier="quick", abpt=abpt)
    fa = os.path.join(DATA_DIR, "sim2k.fa")
    obs.start_run()
    msa_from_file(Abpoa(), abpt, fa, io.StringIO())
    rep = obs.finalize_report()
    comp = rep.get("compiles")
    assert comp is not None and comp["misses"] == 0, comp
    assert rep["counters"].get("fused.recompiles", 0) == 0
