"""Parity tests for the single-dispatch all-device progressive POA loop.

The fused loop (abpoa_tpu/align/fused_loop.py) must produce byte-identical
consensus to the host engines for every in-scope configuration; these tests
compare against the native/numpy path, which is itself byte-golden against the
reference binary (tests/test_golden.py).
"""
import io
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tests.conftest import DATA_DIR, GOLDEN_DIR  # noqa: E402

from abpoa_tpu.params import Params  # noqa: E402
from abpoa_tpu.pipeline import Abpoa, msa_from_file  # noqa: E402
from abpoa_tpu.io.fastx import read_fastx  # noqa: E402


def _consensus_via_fused(path, **kw):
    from abpoa_tpu.align.fused_loop import progressive_poa_fused
    from abpoa_tpu.cons.consensus import generate_consensus
    from abpoa_tpu.io.output import output_fx_consensus
    abpt = Params()
    for k, v in kw.items():
        setattr(abpt, k, v)
    abpt.finalize()
    recs = read_fastx(path)
    enc = abpt.char_to_code
    seqs = [enc[np.frombuffer(r.seq.encode(), dtype=np.uint8)].astype(np.uint8)
            for r in recs]
    wgts = [np.ones(len(s), dtype=np.int64) for s in seqs]
    pg, kahn, _ = progressive_poa_fused(seqs, wgts, abpt)
    cons = generate_consensus(pg, abpt, len(seqs))
    out = io.StringIO()
    output_fx_consensus(cons, abpt, out)
    return out.getvalue(), kahn


def _consensus_via_host(path, device="numpy", **kw):
    abpt = Params()
    for k, v in kw.items():
        setattr(abpt, k, v)
    abpt.device = device
    abpt.finalize()
    ab = Abpoa()
    out = io.StringIO()
    msa_from_file(ab, abpt, path, out)
    return out.getvalue()


@pytest.mark.parametrize("fname,kw", [
    ("seq.fa", {}),                                   # convex (default)
    ("seq.fa", {"gap_open2": 0}),                     # affine
    ("seq.fa", {"gap_open1": 0, "gap_open2": 0}),     # linear
    ("test.fa", {}),
    ("heter.fa", {}),
])
def test_fused_matches_host(fname, kw):
    path = os.path.join(DATA_DIR, fname)
    got, _ = _consensus_via_fused(path, **kw)
    want = _consensus_via_host(path, **kw)
    assert got == want


def test_fused_sim2k_with_growth_and_kahn():
    """sim2k exercises capacity growth buckets and the Kahn-repair path for
    spliced-order violations."""
    path = os.path.join(DATA_DIR, "sim2k.fa")
    got, kahn = _consensus_via_fused(path)
    want = _consensus_via_host(path, device="native")
    assert got == want


def test_fused_int16_promotion_boundary(monkeypatch):
    """Mid-run int16 -> int32 promotion (ERR_PROMOTE) must hand off with no
    lost or duplicated reads. A lowered synthetic score limit makes the graph
    cross the bound after a few short reads instead of needing ~16k nodes."""
    import abpoa_tpu.align.fused_loop as fl
    # seq.fa: ~51bp reads (initial bound 106 <= 160 -> starts int16); the
    # graph grows to 89 nodes, crossing ln*e1+o1 > 160 at gn > 78 mid-run
    monkeypatch.setattr(fl, "int16_score_limit", lambda abpt: 160)
    path = os.path.join(DATA_DIR, "seq.fa")
    got, _ = _consensus_via_fused(path)
    want = _consensus_via_host(path)
    assert got == want


@pytest.mark.slow
def test_fused_scale_long_reads(tmp_path):
    """Scale parity (VERDICT round-1 item 8): a 40-read x 4 kb ONT-like set
    drives the graph through multiple capacity-growth buckets (final ~12.5k
    nodes), int16 planes throughout, and repeated Kahn order repairs; the
    consensus must stay byte-identical to the native engine."""
    import subprocess
    path = str(tmp_path / "sim4k_40.fa")
    subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "make_sim.py"),
         "--ref-len", "4000", "--n-reads", "40", "--err", "0.1",
         "--seed", "5", "--out", path], check=True)
    got, kahn = _consensus_via_fused(path)
    want = _consensus_via_host(path, device="native")
    assert got == want
    assert kahn > 0  # the repair path must actually have been exercised


@pytest.mark.parametrize("flags", [["-r1"], ["-r3"], ["-d2"]])
def test_fused_read_id_outputs(flags):
    """MSA / GFA / diploid outputs need per-edge read-id bitsets; the fused
    loop records each read's fusion path on device and replays the bitsets
    on the host (reference abpoa_set_read_id, abpoa_graph.c:465-469)."""
    import subprocess
    fname = "heter.fa" if "-d2" in flags else "seq.fa"
    path = os.path.join(DATA_DIR, fname)

    def cli(device):
        code = (
            "import jax; jax.config.update('jax_platforms','cpu')\n"
            "import sys, runpy\n"
            f"sys.argv = ['abpoa', '--device', {device!r}] + {flags!r} + [{path!r}]\n"
            "runpy.run_module('abpoa_tpu.cli', run_name='__main__')\n")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr[-500:]
        assert "falling back" not in proc.stderr
        return proc.stdout

    assert cli("jax") == cli("numpy")


@pytest.mark.parametrize("gap", ["convex", "affine"])
def test_fused_random_reads_consensus_matches(gap):
    """Random-read consensus parity vs the host loop (ported from the retired
    round-1 device_pipeline prototype when it was deleted)."""
    from test_device_graph import _random_reads
    from abpoa_tpu.align.fused_loop import progressive_poa_fused
    from abpoa_tpu.cons.consensus import generate_consensus
    from abpoa_tpu.pipeline import Abpoa, poa

    rng = np.random.default_rng(11)
    reads = _random_reads(rng, 6, 140)
    abpt = Params()
    abpt.device = "numpy"
    if gap == "affine":
        abpt.gap_open2 = 0
    abpt.finalize()

    ab = Abpoa()
    for r in reads:
        ab.names.append("")
        ab.comments.append("")
        ab.quals.append(None)
        ab.seqs.append("x" * len(r))
        ab.is_rc.append(False)
    weights = [np.ones(len(r), dtype=np.int64) for r in reads]
    poa(ab, abpt, reads, weights, 0)
    cons_host = generate_consensus(ab.graph, abpt, len(reads)).cons_base

    pg, _, _ = progressive_poa_fused(reads, weights, abpt)
    cons_dev = generate_consensus(pg, abpt, len(reads)).cons_base
    assert cons_host == cons_dev


def test_fused_read_id_collision_rate_sim2k():
    """Read-id replay forfeits the device win whenever a sequential-fusion
    collision fires (progressive_poa_fused raises and pipeline falls back to
    the host loop). Pin the collision frequency on realistic data at zero so a
    regression that starts tripping the fallback is caught by CI."""
    from abpoa_tpu.align.fused_loop import progressive_poa_fused
    path = os.path.join(DATA_DIR, "sim2k.fa")
    abpt = Params()
    abpt.out_msa = True          # forces use_read_ids in finalize()
    abpt.finalize()
    assert abpt.use_read_ids
    recs = read_fastx(path)
    enc = abpt.char_to_code
    seqs = [enc[np.frombuffer(r.seq.encode(), dtype=np.uint8)].astype(np.uint8)
            for r in recs]
    wgts = [np.ones(len(s), dtype=np.int64) for s in seqs]
    # raises RuntimeError if any collision fallback fired
    pg, _, _ = progressive_poa_fused(seqs, wgts, abpt)
    assert pg.node_n > 2


@pytest.mark.parametrize("flags", [["-s"], ["-s", "-r1"]])
def test_fused_amb_strand(flags):
    """In-loop ambiguous-strand rescue (reference src/abpoa_align.c:324-345):
    the fused loop aligns the reverse complement in the same dispatch when the
    forward score is under the threshold and keeps the better strand; output
    (including per-read is_rc annotations in MSA mode) must byte-match the
    host loop without falling back."""
    import subprocess
    path = os.path.join(DATA_DIR, "rcmix.fa")

    def cli(device):
        code = (
            "import jax; jax.config.update('jax_platforms','cpu')\n"
            "import sys, runpy\n"
            f"sys.argv = ['abpoa', '--device', {device!r}] + {flags!r} + [{path!r}]\n"
            "runpy.run_module('abpoa_tpu.cli', run_name='__main__')\n")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-500:]
        assert "falling back" not in proc.stderr
        return proc.stdout

    assert cli("jax") == cli("numpy")


@pytest.mark.parametrize("restore", ["seq10.gfa", "seq10.msa"])
def test_fused_incremental_restore(restore):
    """Incremental MSA `-i` through the fused loop: the restored host graph
    is uploaded as the device starting state (reference abpoa_restore_graph,
    src/abpoa_seq.c:608-673) and new reads align/fuse on device; output must
    byte-match the host loop without falling back."""
    import subprocess
    inc = os.path.join(DATA_DIR, restore)
    path = os.path.join(DATA_DIR, "seq4.fa")

    def cli(device):
        code = (
            "import jax; jax.config.update('jax_platforms','cpu')\n"
            "import sys, runpy\n"
            f"sys.argv = ['abpoa', '--device', {device!r}, '-i', {inc!r}, "
            f"{path!r}]\n"
            "runpy.run_module('abpoa_tpu.cli', run_name='__main__')\n")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-500:]
        assert "falling back" not in proc.stderr
        return proc.stdout

    assert cli("jax") == cli("numpy")


def test_fused_pipeline_wiring():
    """device=jax routes the plain progressive loop through the fused path."""
    path = os.path.join(DATA_DIR, "seq.fa")
    got = _consensus_via_host(path, device="jax")
    want = _consensus_via_host(path, device="numpy")
    assert got == want


@pytest.mark.parametrize("flags", [["-m2"], ["-m2", "-z", "50"],
                                   ["-m2", "-z", "5"]])
def test_fused_extend_zdrop(flags):
    """Extend mode (+ optional Z-drop) through the fused loop: the banded DP
    tracks the running best cell and Z-drop exit exactly like the reference
    (set_extend_max_score, src/abpoa_align_simd.c:1082-1090); output must
    byte-match the host loop without falling back."""
    import subprocess
    path = os.path.join(DATA_DIR, "seq.fa")

    def cli(device):
        code = (
            "import jax; jax.config.update('jax_platforms','cpu')\n"
            "import sys, runpy\n"
            f"sys.argv = ['abpoa', '--device', {device!r}] + {flags!r} + [{path!r}]\n"
            "runpy.run_module('abpoa_tpu.cli', run_name='__main__')\n")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-500:]
        assert "falling back" not in proc.stderr
        return proc.stdout

    got = cli("jax")
    assert got == cli("numpy")
    if flags == ["-m2"]:
        with open(os.path.join(GOLDEN_DIR, "seq_m2.txt")) as fp:
            assert got == fp.read()


@pytest.mark.parametrize("kw", [
    {},                                    # convex
    {"gap_open2": 0},                      # affine
    {"gap_open1": 0, "gap_open2": 0},      # linear
], ids=["convex", "affine", "linear"])
def test_fused_local_mode(kw):
    """Local mode (-m1) through the fused device loop: unbanded full-width
    rows with 0-clamp, best-anywhere (leftmost/earliest) cell, backtrack
    stopping at H == 0 (reference: local clamp abpoa_align_simd.c:1060-1072,
    banding disabled in abpoa_post_set_para); byte parity with the numpy
    oracle and the frozen -m1 golden."""
    path = os.path.join(DATA_DIR, "seq.fa")
    got, _ = _consensus_via_fused(path, align_mode=1, **kw)
    want = _consensus_via_host(path, align_mode=1, **kw)
    assert got == want
    if not kw:
        with open(os.path.join(GOLDEN_DIR, "seq_m1.txt")) as fp:
            assert got == fp.read()


def test_fused_local_random_stress(tmp_path):
    """Local mode on a random high-error read set (denser aligned-node
    groups and more 0-clamped regions than the shipped data): fused device
    loop vs the numpy oracle, byte parity."""
    from test_property import _random_reads
    rng = np.random.default_rng(29)
    reads = _random_reads(rng, 8, 200, err=0.2)
    fa = tmp_path / "loc.fa"
    fa.write_text("".join(
        f">r{i}\n" + "".join("ACGT"[b] for b in r) + "\n"
        for i, r in enumerate(reads)))
    got, _ = _consensus_via_fused(str(fa), align_mode=1)
    want = _consensus_via_host(str(fa), align_mode=1)
    assert got == want
