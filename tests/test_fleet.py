"""Fleet tests (ISSUE 16): replica placement, exactly-once failover,
hedged retries, shed/Retry-After propagation through the proxy hop,
rolling restarts that never drop below N-1 ready, supervisor respawn,
and the end-to-end chaos path (`serve --replicas 2`, SIGKILL one replica
mid-request, every response still a 200 byte-identical to the oracle,
`abpoa-tpu why` names the hop, SIGTERM drains the fleet rc=0).

Router mechanics run against in-process STUB replicas (scripted 200 /
shed / connection-reset behaviors — no serve startup cost); supervisor
mechanics run against a fake replica subprocess that speaks just enough
of the serve contract (listening line, /readyz, /healthz, SIGTERM/SIGHUP
exit); one subprocess test runs the real thing because signals, exit
codes and archive layout ARE the contract."""
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from conftest import DATA_DIR

TEST_FA = os.path.join(DATA_DIR, "test.fa")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------- #
# stub replicas: scripted POST /align behaviors behind the real          #
# readyz/healthz/metrics surface the router polls                        #
# --------------------------------------------------------------------- #

class StubReplica:
    """mode: 'ok' answers 200 (after `delay`), 'shed' answers 429 with
    `retry_after`, 'reset' reads the body then drops the connection
    without a status line (what a SIGKILLed replica looks like)."""

    def __init__(self, name, mode="ok", delay=0.0, retry_after="7",
                 queue_depth=0, open_groups=None):
        self.name = name
        self.mode = mode
        self.delay = delay
        self.retry_after = retry_after
        self.queue_depth = queue_depth
        # continuous batching: boardable in-flight lockstep groups this
        # replica advertises (/healthz open_groups block)
        self.open_groups = open_groups
        self.seen = []          # (rid, attempt) per POST /align received
        self._lock = threading.Lock()
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, body, headers=None):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/readyz":
                    self._send(200, b'{"status": "ready"}')
                elif self.path == "/healthz":
                    doc = {"status": "ok", "queue_depth": stub.queue_depth,
                           "inflight": 0, "replica": stub.name}
                    if stub.open_groups is not None:
                        doc["open_groups"] = stub.open_groups
                    self._send(200, json.dumps(doc).encode())
                elif self.path == "/metrics":
                    text = ("# HELP stub_requests_total served\n"
                            "# TYPE stub_requests_total counter\n"
                            f"stub_requests_total {len(stub.seen)}\n")
                    self._send(200, text.encode())
                else:
                    self._send(404, b"{}")

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                with stub._lock:
                    stub.seen.append(
                        (self.headers.get("X-Abpoa-Request-Id"),
                         int(self.headers.get("X-Abpoa-Attempt") or 1)))
                if stub.mode == "reset":
                    # no status line, hard close: RemoteDisconnected at
                    # the router — the failover trigger
                    self.close_connection = True
                    try:
                        self.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    return
                if stub.mode == "shed":
                    self._send(429, b'{"error": "shed"}\n',
                               {"Retry-After": stub.retry_after})
                    return
                if stub.delay:
                    time.sleep(stub.delay)
                self._send(200, json.dumps(
                    {"served_by": stub.name}).encode() + b"\n",
                    {"X-Abpoa-Replica": stub.name, "X-Abpoa-Reads": "3"})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.base = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def stub_router(monkeypatch):
    """A FleetRouter over freshly-made stubs; yields a factory, cleans
    everything up. Hedging defaults OFF so tests opt in explicitly."""
    monkeypatch.setenv("ABPOA_TPU_FLEET_HEDGE_S", "off")
    monkeypatch.setenv("ABPOA_TPU_FLEET_POLL_S", "0.1")
    made = []

    def make(*stubs, start_http=False, **kw):
        from abpoa_tpu.serve.router import FleetRouter
        r = FleetRouter(port=0, timeout_s=10.0, **kw)
        for s in stubs:
            r.set_replica(s.name, s.base)
        r.poll_now()
        if start_http:
            r.start()
        made.append((r, stubs, start_http))
        return r

    yield make
    for r, stubs, started in made:
        if started:
            r.stop()
        else:
            r._poll_stop.set()
            r._httpd.server_close()
        for s in stubs:
            s.close()


# --------------------------------------------------------------------- #
# placement                                                              #
# --------------------------------------------------------------------- #

def test_plan_placement_orders_by_load_then_rung_affinity():
    from abpoa_tpu.serve.router import ReplicaView, plan_placement
    a = ReplicaView("r0", "http://x:1")
    b = ReplicaView("r1", "http://x:2")
    c = ReplicaView("r2", "http://x:3")
    for v in (a, b, c):
        v.ready = True
    a.queue_depth = 4                   # loaded
    b.last_rung = 256                   # idle, warm at the target rung
    c.local_inflight = 1                # one router send outstanding
    order = [v.name for v in plan_placement([a, b, c], rung=256)]
    assert order == ["r1", "r2", "r0"]
    # rung affinity is only a tie-break: it never outranks load
    b.queue_depth = 9
    assert [v.name for v in plan_placement([a, b, c], rung=256)][0] == "r2"
    # not-ready and draining replicas never place
    c.draining = True
    a.ready = False
    assert [v.name for v in plan_placement([a, b, c], rung=256)] == ["r1"]


def test_plan_placement_prefers_open_same_rung_group():
    """Continuous batching (PR 17): a replica advertising a boardable
    in-flight group on the request's rung outranks one that merely served
    the rung last — the request joins at the next round boundary instead
    of waiting out a fresh group. Load still dominates affinity."""
    from abpoa_tpu.serve.router import ReplicaView, plan_placement
    warm = ReplicaView("r0", "http://x:1")
    boardable = ReplicaView("r1", "http://x:2")
    cold = ReplicaView("r2", "http://x:3")
    for v in (warm, boardable, cold):
        v.ready = True
    warm.last_rung = 256
    boardable.health = {"open_groups": [
        {"id": 3, "rung": 256, "free": 2, "round": 5, "live": 6}]}
    order = [v.name for v in plan_placement([warm, boardable, cold],
                                            rung=256)]
    assert order == ["r1", "r0", "r2"]
    # a full group (free=0) is not boardable: warm-cache affinity wins
    boardable.health = {"open_groups": [
        {"id": 3, "rung": 256, "free": 0, "round": 5, "live": 8}]}
    assert [v.name for v in plan_placement(
        [warm, boardable, cold], rung=256)][0] == "r0"
    # an open group on a DIFFERENT rung gives no affinity either
    boardable.health = {"open_groups": [
        {"id": 3, "rung": 512, "free": 2, "round": 5, "live": 6}]}
    assert [v.name for v in plan_placement(
        [warm, boardable, cold], rung=256)][0] == "r0"
    # open-group affinity never outranks load
    boardable.health = {"open_groups": [
        {"id": 3, "rung": 256, "free": 2, "round": 5, "live": 6}]}
    boardable.queue_depth = 9
    assert [v.name for v in plan_placement(
        [warm, boardable, cold], rung=256)][0] == "r0"


def test_router_polls_open_groups_block(stub_router):
    """The health poller stores the full /healthz doc, so a stub replica's
    open_groups block is visible to placement through the poll path."""
    s0 = StubReplica("r0", open_groups=[
        {"id": 1, "rung": 128, "free": 3, "round": 2, "live": 5}])
    r = stub_router(s0)
    v = r.views()[0]
    assert v.open_group_rungs() == {128}


def test_router_routes_to_ready_replica_with_attribution(stub_router):
    s0 = StubReplica("r0")
    r = stub_router(s0)
    out = r.route(b">s\nACGT\n", {}, "rid-basic")
    assert out.code == 200
    assert out.replica == "r0" and out.attempt == 1
    assert out.failovers == 0 and out.hedges == 0
    assert s0.seen == [("rid-basic", 1)]


def test_router_503_when_no_replica_ready(stub_router):
    r = stub_router()          # no replicas registered at all
    out = r.route(b">s\nACGT\n", {}, "rid-none")
    assert out.code == 503
    assert out.headers.get("Retry-After")


# --------------------------------------------------------------------- #
# failover                                                               #
# --------------------------------------------------------------------- #

def test_failover_exactly_once_same_rid_bumped_attempt(stub_router):
    dead = StubReplica("r0", mode="reset")
    live = StubReplica("r1", queue_depth=5)   # loaded: r0 places first
    r = stub_router(dead, live)
    out = r.route(b">s\nACGT\n", {}, "rid-fo")
    assert out.code == 200 and out.replica == "r1"
    assert out.failovers == 1 and out.attempt == 2
    # exactly one delivery per replica, same id across the hop, attempt
    # bumped on the retry — the idempotent-archive-record invariant
    assert dead.seen == [("rid-fo", 1)]
    assert live.seen == [("rid-fo", 2)]


def test_failover_never_retries_twice(stub_router):
    d0 = StubReplica("r0", mode="reset")
    d1 = StubReplica("r1", mode="reset")
    r = stub_router(d0, d1)
    out = r.route(b">s\nACGT\n", {}, "rid-fo2")
    assert out.code == 502           # both transports died, no third try
    assert out.failovers == 1
    assert len(d0.seen) + len(d1.seen) == 2


# --------------------------------------------------------------------- #
# shed propagation                                                       #
# --------------------------------------------------------------------- #

def test_all_shed_propagates_last_retry_after(stub_router):
    s0 = StubReplica("r0", mode="shed", retry_after="7")
    s1 = StubReplica("r1", mode="shed", retry_after="11", queue_depth=3)
    r = stub_router(s0, s1)
    out = r.route(b">s\nACGT\n", {}, "rid-shed")
    assert out.code == 429
    # spill order is r0 (idle) then r1; the propagated Retry-After is the
    # final shedder's, verbatim
    assert out.headers.get("Retry-After") == "11"
    assert s0.seen == [("rid-shed", 1)] and s1.seen == [("rid-shed", 2)]


def test_shed_spills_to_sibling_that_accepts(stub_router):
    s0 = StubReplica("r0", mode="shed", retry_after="7")
    s1 = StubReplica("r1", queue_depth=9)     # loaded but willing
    r = stub_router(s0, s1)
    out = r.route(b">s\nACGT\n", {}, "rid-spill")
    assert out.code == 200 and out.replica == "r1"
    assert out.failovers == 0                 # a shed is not a failover


# --------------------------------------------------------------------- #
# hedged retries                                                         #
# --------------------------------------------------------------------- #

def test_hedge_first_response_wins_duplicate_discarded(stub_router,
                                                       monkeypatch):
    slow = StubReplica("r0", delay=1.5)
    fast = StubReplica("r1", queue_depth=1)   # r0 places first
    r = stub_router(slow, fast)
    monkeypatch.setenv("ABPOA_TPU_FLEET_HEDGE_S", "0.1")
    out = r.route(b">s\nACGT\n", {}, "rid-hedge")
    assert out.code == 200 and out.replica == "r1"
    assert out.hedges == 1 and out.hedge_won and out.attempt == 2
    # the slow primary still completes in its daemon thread and is
    # discarded idempotently — one delivery per replica, no crash
    deadline = time.time() + 5
    while len(slow.seen) < 1 and time.time() < deadline:
        time.sleep(0.05)
    assert slow.seen == [("rid-hedge", 1)]
    assert fast.seen == [("rid-hedge", 2)]


def test_hedge_delay_derives_from_sketch_and_env(monkeypatch):
    from abpoa_tpu.obs.metrics import LogSketch
    from abpoa_tpu.serve.router import hedge_delay_s
    sk = LogSketch()
    monkeypatch.delenv("ABPOA_TPU_FLEET_HEDGE_S", raising=False)
    assert hedge_delay_s(sk) is None          # cold sketch: no hedging
    for _ in range(50):
        sk.observe(0.2)
    d = hedge_delay_s(sk)
    assert d is not None and 0.3 < d < 0.5    # ~2x p95 within tolerance
    monkeypatch.setenv("ABPOA_TPU_FLEET_HEDGE_S", "off")
    assert hedge_delay_s(sk) is None
    monkeypatch.setenv("ABPOA_TPU_FLEET_HEDGE_S", "1.25")
    assert hedge_delay_s(sk) == 1.25


# --------------------------------------------------------------------- #
# connection semantics through the proxy hop (satellite 4)               #
# --------------------------------------------------------------------- #

def _raw_post(host, port, body=b">s\nACGT\n", cl=None):
    """One POST over a raw http.client connection; returns (status,
    headers, connection) with the response fully read — the caller can
    then PROVE keep-alive by reusing the same connection."""
    conn = http.client.HTTPConnection(host, port, timeout=10)
    headers = {"Content-Type": "text/x-fasta"}
    if cl is not None:
        headers["Content-Length"] = cl
    conn.request("POST", "/align", body=body, headers=headers)
    resp = conn.getresponse()
    resp.read()
    return resp.status, dict(resp.getheaders()), conn


def _assert_conn_closed(conn):
    """The server must have CLOSED the keep-alive socket (the
    single-process semantics for every body-unread disposition): a
    second request on the same connection cannot complete."""
    with pytest.raises((http.client.HTTPException, ConnectionError,
                        OSError)):
        conn.request("POST", "/align", body=b">s\nACGT\n",
                     headers={"Content-Type": "text/x-fasta"})
        conn.getresponse().read()
    conn.close()


def test_router_draining_503_closes_with_retry_after(stub_router):
    s0 = StubReplica("r0")
    r = stub_router(s0, start_http=True)
    r.begin_drain()
    status, headers, conn = _raw_post("127.0.0.1", r.port)
    assert status == 503
    assert headers.get("Retry-After") == "30"       # serve's exact value
    _assert_conn_closed(conn)


def test_router_oversized_413_closes(stub_router, monkeypatch):
    monkeypatch.setenv("ABPOA_TPU_SERVE_MAX_BODY_MB", "0.00001")  # 10 B
    s0 = StubReplica("r0")
    r = stub_router(s0, start_http=True)
    status, headers, conn = _raw_post("127.0.0.1", r.port,
                                      body=b">s\n" + b"A" * 64 + b"\n")
    assert status == 413
    assert s0.seen == []          # never proxied
    _assert_conn_closed(conn)


def test_router_malformed_content_length_400_closes(stub_router):
    s0 = StubReplica("r0")
    r = stub_router(s0, start_http=True)
    with socket.create_connection(("127.0.0.1", r.port),
                                  timeout=10) as sk:
        sk.sendall(b"POST /align HTTP/1.1\r\nHost: x\r\n"
                   b"Content-Length: zzz\r\n\r\n")
        data = sk.recv(4096)
        assert b" 400 " in data.split(b"\r\n", 1)[0]
        # the server actually closes: EOF, not a hung keep-alive
        sk.settimeout(5)
        rest = b"x"
        while rest:
            rest = sk.recv(4096)
    assert s0.seen == []


def test_proxied_shed_keeps_connection_alive_no_desync(stub_router):
    """Regression: a proxied 429 must NOT close (the router read the
    client body), and the SAME client connection must cleanly carry the
    next request — no keep-alive desync through the proxy hop."""
    s0 = StubReplica("r0", mode="shed", retry_after="7")
    r = stub_router(s0, start_http=True)
    status, headers, conn = _raw_post("127.0.0.1", r.port)
    assert status == 429
    assert headers.get("Retry-After") == "7"        # propagated verbatim
    assert headers.get("Connection") != "close"
    # second request on the same socket: proves framing stayed aligned
    s0.mode = "ok"
    conn.request("POST", "/align", body=b">s\nACGT\n",
                 headers={"Content-Type": "text/x-fasta"})
    resp = conn.getresponse()
    body = resp.read()
    assert resp.status == 200 and b"served_by" in body
    assert resp.getheader("X-Abpoa-Replica") == "r0"
    assert resp.getheader("X-Abpoa-Attempt") == "1"
    conn.close()


def test_router_metrics_endpoint_merges_replica_expositions(stub_router):
    s0, s1 = StubReplica("r0"), StubReplica("r1")
    r = stub_router(s0, s1, start_http=True)
    r.route(b">s\nACGT\n", {}, "rid-m")   # one routed request on record
    with urllib.request.urlopen(
            f"http://127.0.0.1:{r.port}/metrics", timeout=10) as resp:
        text = resp.read().decode()
    from abpoa_tpu.obs import metrics as M
    assert M.lint_exposition(text) == []
    samples, _types = M.parse_exposition(text)
    # replica families sum across the fleet; router families ride along
    assert M.sample_value(samples, "stub_requests_total") >= 1
    assert M.sample_value(samples, "abpoa_fleet_requests_total",
                          status="ok") >= 1


# --------------------------------------------------------------------- #
# supervisor over fake replicas                                          #
# --------------------------------------------------------------------- #

FAKE_REPLICA = r'''
import json, os, signal, sys, threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

name = os.environ.get("ABPOA_TPU_REPLICA", "?")


class H(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _j(self, code, obj):
        b = (json.dumps(obj) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(b)))
        self.end_headers()
        self.wfile.write(b)

    def do_GET(self):
        if self.path == "/readyz":
            self._j(200, {"status": "ready"})
        elif self.path == "/healthz":
            self._j(200, {"status": "ok", "queue_depth": 0, "inflight": 0,
                          "pid": os.getpid()})
        elif self.path == "/metrics":
            b = ("# HELP fake_up replica liveness\n"
                 "# TYPE fake_up gauge\nfake_up 1\n").encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(b)))
            self.end_headers()
            self.wfile.write(b)
        else:
            self._j(404, {})

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        self.rfile.read(n)
        self._j(200, {"pid": os.getpid(), "replica": name})


srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
srv.daemon_threads = True
stop = threading.Event()
for s in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
    signal.signal(s, lambda *a: stop.set())
threading.Thread(target=srv.serve_forever, daemon=True).start()
print(f"[fake {name}] listening on "
      f"http://127.0.0.1:{srv.server_address[1]}",
      file=sys.stderr, flush=True)
stop.wait()
srv.shutdown()
sys.exit(0)
'''


@pytest.fixture
def fake_fleet(tmp_path, monkeypatch):
    monkeypatch.setenv("ABPOA_TPU_FLEET_POLL_S", "0.1")
    monkeypatch.setenv("ABPOA_TPU_POOL_BACKOFF_S", "0.1")
    monkeypatch.setenv("ABPOA_TPU_FLEET_STALL_S", "0")
    script = tmp_path / "fake_replica.py"
    script.write_text(FAKE_REPLICA)
    sups = []

    def make(n):
        from abpoa_tpu.serve.fleet import FleetSupervisor
        sup = FleetSupervisor(
            n, archive_base=str(tmp_path / "reports"),
            replica_cmd=lambda i, name, argv: [sys.executable,
                                               str(script)])
        sup.start()
        runner = threading.Thread(target=sup.run_forever,
                                  kwargs={"tick_s": 0.05}, daemon=True)
        runner.start()
        deadline = time.time() + 30
        while sup.router.ready_count() < n and time.time() < deadline:
            time.sleep(0.05)
        assert sup.router.ready_count() == n, "fleet never became ready"
        sups.append(sup)
        return sup

    yield make
    for sup in sups:
        sup.shutdown()


def test_supervisor_respawns_sigkilled_replica(fake_fleet):
    sup = fake_fleet(2)
    victim = sup.replicas[0]
    old_pid = victim.proc.pid
    os.kill(old_pid, signal.SIGKILL)
    deadline = time.time() + 20
    while time.time() < deadline:
        if (sup.router.ready_count() == 2 and victim.proc is not None
                and victim.proc.pid != old_pid):
            break
        time.sleep(0.05)
    assert victim.proc is not None and victim.proc.pid != old_pid
    assert sup.router.ready_count() == 2
    assert victim.respawns >= 1


def test_rolling_restart_never_below_n_minus_1_ready(fake_fleet):
    sup = fake_fleet(3)
    before = [r.proc.pid for r in sup.replicas]
    samples = []
    done = threading.Event()

    def sample():
        while not done.is_set():
            samples.append(sup.router.ready_count())
            time.sleep(0.02)

    t = threading.Thread(target=sample, daemon=True)
    t.start()
    sup.rolling_restart(ready_timeout=30)
    done.set()
    t.join(5)
    after = [r.proc.pid for r in sup.replicas]
    assert all(a != b for a, b in zip(after, before)), \
        "every replica must have been restarted"
    assert samples and min(samples) >= 2, \
        f"ready capacity dipped below N-1: min={min(samples)}"
    assert sup.router.ready_count() == 3


# --------------------------------------------------------------------- #
# end-to-end: the real fleet, a real SIGKILL, the real archive           #
# --------------------------------------------------------------------- #

def _oracle_bytes(path=TEST_FA):
    import io
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa
    abpt = Params()
    abpt.device = "numpy"
    buf = io.StringIO()
    msa(Abpoa(), abpt.finalize(), read_fastx(path), buf)
    return buf.getvalue().encode()


def _post(base, body, timeout=60):
    req = urllib.request.Request(base + "/align", data=body,
                                 method="POST",
                                 headers={"Content-Type": "text/x-fasta"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def test_fleet_e2e_sigkill_failover_why_and_drain(tmp_path):
    """The chaos acceptance path in miniature: 2 numpy replicas, SIGKILL
    one with requests in flight -> every response is still a 200
    byte-identical to the oracle (the killed replica's via attempt 2 on
    the sibling), `why` names the hop across replica archives, SIGTERM
    drains the whole fleet rc=0."""
    archive_base = str(tmp_path / "reports")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               ABPOA_TPU_SKIP_PROBE="1",
               ABPOA_TPU_ARCHIVE="1",
               ABPOA_TPU_ARCHIVE_DIR=archive_base,
               ABPOA_TPU_SERVE_DELAY_S="1.0",
               ABPOA_TPU_FLEET_POLL_S="0.1",
               ABPOA_TPU_FLEET_HEDGE_S="off")
    proc = subprocess.Popen(
        [sys.executable, "-m", "abpoa_tpu.cli", "serve", "--replicas", "2",
         "--port", "0", "--device", "numpy", "--workers", "2",
         "--warm", "off"],
        cwd=REPO, env=env, stderr=subprocess.PIPE, text=True)
    stderr_tail = []
    try:
        port = None
        deadline = time.time() + 120
        while time.time() < deadline:
            line = proc.stderr.readline()
            if not line:
                assert proc.poll() is None, "fleet died during startup"
                continue
            stderr_tail.append(line)
            if "[abpoa-tpu fleet] listening on http://" in line:
                port = int(line.split("listening on http://")[1]
                           .split()[0].rsplit(":", 1)[1])
                break
        assert port, "fleet never printed its listening line"
        threading.Thread(
            target=lambda: stderr_tail.extend(proc.stderr),
            daemon=True).start()
        base = f"http://127.0.0.1:{port}"
        deadline = time.time() + 120
        ready = False
        while time.time() < deadline and not ready:
            try:
                with urllib.request.urlopen(base + "/readyz",
                                            timeout=2) as r:
                    ready = r.status == 200
            except (urllib.error.URLError, OSError):
                time.sleep(0.2)
        assert ready, "fleet router never became ready"
        # wait for BOTH replicas so the kill leaves a sibling
        deadline = time.time() + 60
        pids = {}
        while time.time() < deadline:
            with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
                doc = json.loads(r.read())
            if doc.get("ready") == 2:
                pids = doc["fleet"]["pids"]
                break
            time.sleep(0.2)
        assert len(pids) == 2, f"fleet never reached 2 ready: {doc}"

        body = open(TEST_FA, "rb").read()
        results = {}

        def post(i):
            results[i] = _post(base, body, timeout=90)

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
            time.sleep(0.05)
        time.sleep(0.4)        # all four in flight (1 s service time)
        os.kill(pids["r0"], signal.SIGKILL)
        for t in threads:
            t.join(120)
        oracle = _oracle_bytes()
        assert all(code == 200 for code, _b, _h in results.values()), \
            {i: r[0] for i, r in results.items()}
        assert all(b == oracle for _c, b, _h in results.values())
        hopped = [h for _c, _b, h in results.values()
                  if int(h.get("X-Abpoa-Failovers") or 0) >= 1]
        assert hopped, "no request recorded a failover hop " \
                       f"({[r[2] for r in results.values()]})"
        assert all(int(h.get("X-Abpoa-Attempt") or 1) > 1
                   for h in hopped)
        rid = hopped[0]["X-Abpoa-Request-Id"]

        # `why` resolves the id across the replica archives and names
        # the hop (the killed attempt left no record; the surviving
        # record's attempt number tells the story)
        why = subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", "why", rid,
             "--fleet", "--archive-dir", archive_base],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        assert why.returncode == 0, why.stderr
        assert rid in why.stdout
        assert "attempt" in why.stdout and "replica" in why.stdout

        # `slo --fleet` evaluates the merged replica window
        slo = subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", "slo", "--fleet",
             "--archive-dir", archive_base],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        assert slo.returncode == 0, slo.stdout + slo.stderr
        assert "replica archives" in slo.stdout

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        assert rc == 0, f"fleet drain rc={rc}"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    tail = "".join(stderr_tail)
    assert "drained clean" in tail
    assert "Traceback" not in tail
    # the surviving replica's archive holds the attempt-2 record under
    # the fleet layout slo/why just read
    surviving = os.path.join(archive_base, "replica-r1", "reports.jsonl")
    assert os.path.exists(surviving)
